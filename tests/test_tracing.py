"""Distributed tracing + fleet aggregation (ISSUE 12): span semantics
(nesting, exception safety, context propagation through rpc), the
Chrome/Perfetto exporter (golden JSON, stability, escaping), compile
span / retrace-cause events from the jit layer, HBM gauges,
``fleet_snapshot`` merge + skew on a simulated 8-rank fleet (including
the straggler-timeout path), flight-dump schema v2, and the
``PDTPU_METRICS=off`` cheap-no-op parity.

Everything is model-free and sub-second except the export acceptance
drill, which reuses the session tiny GPT (``conftest.serving_gpt``)
and the geometries the serving suite already compiled.
"""
import json
import math
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import observability as obs
from paddle_tpu.observability import aggregate, tracing
from paddle_tpu.observability.metrics import Registry


@pytest.fixture
def metrics_on():
    old = paddle.get_flags("metrics")["metrics"]
    paddle.set_flags({"metrics": True})
    yield
    paddle.set_flags({"metrics": old})


@pytest.fixture
def fresh_trace(metrics_on):
    """Clean ring + deterministic span/trace ids for golden output."""
    obs.events.clear()
    tracing._reset()
    yield
    tracing._reset()
    obs.events.clear()


# ==========================================================================
# span semantics
# ==========================================================================

def test_span_nesting_and_context(fresh_trace):
    with tracing.span("outer", phase="x"):
        ctx = tracing.inject()
        assert ctx == {"trace_id": 1, "span_id": 2}
        assert tracing.context_fields() == {"trace_id": 1,
                                            "parent_id": 2}
        with tracing.span("inner"):
            pass
    evs = obs.tail()
    kinds = [(e["kind"], e["name"]) for e in evs]
    assert kinds == [("span.begin", "outer"), ("span.begin", "inner"),
                     ("span.end", "inner"), ("span.end", "outer")]
    beg_outer, beg_inner, end_inner, end_outer = evs
    assert beg_outer["trace_id"] == beg_inner["trace_id"]
    assert "parent_id" not in beg_outer              # root
    assert beg_inner["parent_id"] == beg_outer["span_id"]
    assert end_inner["dur_us"] >= 0
    assert beg_outer["phase"] == "x"
    # trace closed: context empty, next root starts a NEW trace
    assert tracing.inject() is None
    with tracing.span("again"):
        assert tracing.inject()["trace_id"] != beg_outer["trace_id"]


def test_span_exception_safety(fresh_trace):
    with pytest.raises(ValueError):
        with tracing.span("boom"):
            raise ValueError("x")
    end = obs.tail()[-1]
    assert end["kind"] == "span.end" and end["error"] == "ValueError"
    # the stack unwound: a new span is a fresh root
    assert tracing.inject() is None
    with tracing.span("after"):
        assert "parent_id" not in obs.tail()[-1]


def test_traced_decorator(fresh_trace):
    @tracing.traced
    def work():
        return 7

    @tracing.traced("named", k=1)
    def work2():
        return 8

    assert work() == 7 and work2() == 8
    names = [e["name"] for e in obs.tail()
             if e["kind"] == "span.begin"]
    assert names == ["work", "named"]


def test_attach_reparents_spans(fresh_trace):
    with tracing.span("client"):
        ctx = tracing.inject()
    with tracing.attach(ctx), tracing.span("server"):
        pass
    beg = [e for e in obs.tail() if e["kind"] == "span.begin"]
    assert beg[1]["name"] == "server"
    assert beg[1]["trace_id"] == ctx["trace_id"]
    assert beg[1]["parent_id"] == ctx["span_id"]
    # attach scope popped cleanly
    assert tracing.inject() is None
    assert tracing.attach(None).__enter__() is not None  # no-op ok


# ==========================================================================
# Chrome trace export
# ==========================================================================

def test_render_trace_golden():
    """Exact export of a synthetic ring: span pair fused to one "X"
    complete event, serving lifecycle on slot tracks, fault event on
    the runtime track, metadata first, stable sorted JSON, standard
    escaping of a quote/newline payload."""
    events = [
        {"seq": 0, "ts": 100.0, "kind": "span.begin", "name": "compile",
         "span_id": 2, "trace_id": 1, "tname": "MainThread", "fn": "step"},
        {"seq": 1, "ts": 100.002, "kind": "span.end", "name": "compile",
         "span_id": 2, "trace_id": 1, "dur_us": 2000.0},
        {"seq": 2, "ts": 100.003, "kind": "serving.enqueued", "rid": 0,
         "prompt_len": 4, "max_new_tokens": 2},
        {"seq": 3, "ts": 100.004, "kind": "serving.admitted", "rid": 0,
         "slot": 1, "cached_tokens": 0, "resume_len": 0},
        {"seq": 4, "ts": 100.005, "kind": "fault.fired",
         "site": "engine_nan_decode", "key": 'r"0\n'},
    ]
    got = tracing.render_trace(events, rank=3, host="tpu-worker-3")
    assert got == {
        "displayTimeUnit": "ms",
        "traceEvents": [
            {"name": "process_name", "ph": "M", "pid": 3, "tid": 0,
             "args": {"name": "rank3 (tpu-worker-3)"}},
            {"name": "thread_name", "ph": "M", "pid": 3, "tid": 1,
             "args": {"name": "MainThread"}},
            {"name": "thread_name", "ph": "M", "pid": 3, "tid": 2,
             "args": {"name": "engine"}},
            {"name": "thread_name", "ph": "M", "pid": 3, "tid": 3,
             "args": {"name": "engine/slot1"}},
            {"name": "thread_name", "ph": "M", "pid": 3, "tid": 4,
             "args": {"name": "runtime"}},
            {"name": "compile", "cat": "span", "ph": "X", "ts": 0.0,
             "dur": 2000.0, "pid": 3, "tid": 1,
             "args": {"span_id": 2, "trace_id": 1, "fn": "step"}},
            {"name": "serving.enqueued", "cat": "serving", "ph": "i",
             "s": "t", "ts": 3000.0, "pid": 3, "tid": 2,
             "args": {"rid": 0, "prompt_len": 4, "max_new_tokens": 2}},
            {"name": "serving.admitted", "cat": "serving", "ph": "i",
             "s": "t", "ts": 4000.0, "pid": 3, "tid": 3,
             "args": {"rid": 0, "slot": 1, "cached_tokens": 0,
                      "resume_len": 0}},
            {"name": "fault.fired", "cat": "fault", "ph": "i",
             "s": "t", "ts": 5000.0, "pid": 3, "tid": 4,
             "args": {"site": "engine_nan_decode", "key": 'r"0\n'}},
        ],
    }
    # serialization is valid, stable JSON (escaping included)
    s1 = json.dumps(got, indent=1, sort_keys=True)
    assert json.loads(s1) == got
    assert s1 == json.dumps(tracing.render_trace(
        events, rank=3, host="tpu-worker-3"), indent=1, sort_keys=True)


def test_render_trace_unmatched_spans():
    """A begin whose end fell off the ring renders as "B" (the open
    phase a crash trace ends in); an orphan end renders as "E"."""
    events = [
        {"seq": 0, "ts": 1.0, "kind": "span.begin", "name": "hung",
         "span_id": 9, "trace_id": 5, "tname": "MainThread"},
        {"seq": 1, "ts": 1.5, "kind": "span.end", "name": "lost",
         "span_id": 8, "trace_id": 5, "dur_us": 10.0},
    ]
    evs = tracing.render_trace(events)["traceEvents"]
    phases = {e["name"]: e["ph"] for e in evs if e["ph"] in "BE"}
    assert phases == {"hung": "B", "lost": "E"}


def test_export_trace_acceptance(serving_gpt, fresh_trace, tmp_path):
    """ISSUE 12 acceptance: export of a serving-engine run + a 2-rank
    CPU-mesh training segment is valid Chrome trace JSON containing
    engine lifecycle spans, a collective span, and a compile span."""
    import paddle_tpu.distributed as dist
    import paddle_tpu.nn as nn
    from paddle_tpu.inference import ContinuousBatchingEngine

    # --- serving half: lifecycle events + dispatch spans
    rng = np.random.default_rng(0)
    eng = ContinuousBatchingEngine(serving_gpt, max_slots=2, page_size=8,
                                   max_seq_len=32, decode_window=4,
                                   prefill_chunk=8, q_block=2)
    for n, new in ((5, 6), (9, 4)):
        eng.add_request(rng.integers(0, 96, (n,)).astype(np.int32), new)
    eng.run()

    # --- training half: 2-rank group, eager DP sync (collective span)
    # + a to_static capture (compile span)
    g = dist.new_group([0, 1])
    net = dist.DataParallel(nn.Linear(8, 8), group=g)
    opt = paddle.optimizer.SGD(parameters=net.parameters())
    x = paddle.to_tensor(np.ones((4, 8), "float32"))
    loss = (net(x) ** 2).mean()
    loss.backward()
    net.apply_collective_grads()
    opt.step()
    opt.clear_grad()

    fresh = nn.Linear(8, 8)

    @paddle.jit.to_static
    def step(inp):
        return (fresh(inp) ** 2).mean()

    step(x)

    path = tracing.export_trace(str(tmp_path / "trace.json"))
    assert path and os.path.exists(path)
    rec = json.load(open(path))
    evs = rec["traceEvents"]
    names = {e["name"] for e in evs}
    assert {"serving.enqueued", "serving.admitted",
            "serving.prefill_chunk", "serving.first_token",
            "serving.retired"} <= names
    spans = {e["name"] for e in evs
             if e.get("cat") == "span" and e["ph"] == "X"}
    assert "serving.dispatch" in spans       # engine dispatch spans
    assert "collective.psum_mean" in spans   # DP grad-sync collective
    assert "dp.grad_sync" in spans
    assert "compile" in spans                # jit capture
    # every complete event has non-negative duration and a track
    tids = {e["tid"]: e for e in evs if e["ph"] == "M"
            and e["name"] == "thread_name"}
    for e in evs:
        if e["ph"] == "X":
            assert e["dur"] >= 0 and e["tid"] in tids
    # slot tracks exist (one track per engine slot)
    track_names = {e["args"]["name"] for e in tids.values()}
    assert any(t.startswith("engine/slot") for t in track_names)


# ==========================================================================
# rpc context propagation
# ==========================================================================

def _remote_probe(x):
    """Runs on the rpc server thread; its span must join the trace."""
    with tracing.span("remote_work"):
        return x + 1


def test_rpc_trace_propagation_roundtrip(fresh_trace):
    from paddle_tpu.distributed import rpc

    rpc.init_rpc("worker0", rank=0, world_size=1)
    try:
        with tracing.span("client_op"):
            assert rpc.rpc_sync("worker0", _remote_probe,
                                args=(41,)) == 42
            # async variant: the context is captured on THE CALLING
            # thread before the worker thread spawns — its rpc.client
            # span must join this trace, not start a new root
            root_ctx = tracing.inject()
            fut = rpc.rpc_async("worker0", _remote_probe, args=(1,))
            assert fut.wait() == 2
    finally:
        rpc.shutdown()
    async_begs = [e for e in obs.tail() if e["kind"] == "span.begin"
                  and e["name"] == "rpc.client"]
    assert len(async_begs) == 2
    assert async_begs[1]["trace_id"] == root_ctx["trace_id"]
    assert async_begs[1]["parent_id"] == root_ctx["span_id"]
    begs = {e["name"]: e for e in obs.tail()
            if e["kind"] == "span.begin"}
    assert {"client_op", "rpc.client", "rpc.server",
            "remote_work"} <= set(begs)
    root = begs["client_op"]
    # ONE trace end to end; parent chain crosses the wire
    for name in ("rpc.client", "rpc.server", "remote_work"):
        assert begs[name]["trace_id"] == root["trace_id"], name
    assert begs["rpc.client"]["parent_id"] == root["span_id"]
    assert begs["rpc.server"]["parent_id"] == \
        begs["rpc.client"]["span_id"]
    assert begs["remote_work"]["parent_id"] == \
        begs["rpc.server"]["span_id"]
    assert begs["rpc.server"]["fn"] == "_remote_probe"


# ==========================================================================
# compile spans, retrace causes, HBM gauges
# ==========================================================================

def test_compile_span_retrace_cause_and_hbm_gauges(fresh_trace):
    import jax

    import paddle_tpu.nn as nn

    reg = obs.registry()
    h0 = reg.histogram("train.compile_ms").count
    net = nn.Linear(4, 4)
    opt = paddle.optimizer.SGD(parameters=net.parameters())

    @paddle.jit.to_static
    def step(x):
        loss = (net(x) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    x = paddle.to_tensor(np.ones((2, 4), "float32"))
    step(x)
    # the capture emitted a compile span with geometry attrs and fed
    # the train.compile_ms histogram
    begs = [e for e in obs.tail() if e["kind"] == "span.begin"
            and e["name"] == "compile"]
    assert begs and begs[-1]["fn"] == "step"
    assert begs[-1]["n_inputs"] >= 1
    assert reg.histogram("train.compile_ms").count == h0 + 1
    # HBM gauges: per-program captured-state bytes + process total
    snap = reg.snapshot()["hbm"]
    assert snap["program_state_bytes"]["fn=step"] > 0
    assert snap["live_bytes"] > 0
    assert snap["live_bytes"] >= snap["program_state_bytes"]["fn=step"]

    exe = step.concrete_program(x)
    assert exe is not None and exe.trace_count == 1
    vals = [t._data for t in [x] + exe.capt_state]

    # identical-signature re-trace (the jit cache-miss / eviction /
    # scan-window class).  jax caches traces by (fun identity, avals),
    # so tracing the SAME pure through a fresh wrapper is exactly the
    # cache-miss event the counter guards against
    jax.make_jaxpr(lambda *v: exe._pure(*v))(*vals)
    retr = [e for e in obs.tail() if e["kind"] == "compile.retrace"]
    assert retr and retr[-1]["count"] == 2
    assert "same signature" in retr[-1]["cause"]

    # changed-shape re-trace names the offending position
    vals2 = [np.ones((6, 4), "float32")] + vals[1:]
    jax.make_jaxpr(exe._pure)(*vals2)
    retr = [e for e in obs.tail() if e["kind"] == "compile.retrace"]
    assert retr[-1]["count"] == 3
    assert "arg0" in retr[-1]["cause"]
    assert "(2, 4)" in retr[-1]["cause"]
    assert "(6, 4)" in retr[-1]["cause"]


# ==========================================================================
# fleet aggregation
# ==========================================================================

def _rank_registry(rank, *, steps=8, step_ms=None, straggle=0.0):
    """One simulated rank's registry: step histogram, a counter, an
    overlap gauge, a phase histogram the attribution can pick up."""
    r = Registry()
    h = r.histogram("train.step_ms",
                    buckets=obs.LATENCY_BUCKETS_MS)
    base = step_ms if step_ms is not None else 10.0
    for _ in range(steps):
        h.observe(base + straggle)
    r.counter("train.steps").inc(steps)
    r.gauge("train.overlap_frac").set(0.9 - 0.1 * (straggle > 0))
    hc = r.histogram("train.comm_ms", buckets=obs.LATENCY_BUCKETS_MS)
    for _ in range(steps):
        hc.observe(1.0 + straggle)
    return r


def test_fleet_snapshot_merge_8_ranks_with_straggler(metrics_on,
                                                     tmp_path):
    """The 8-dev-mesh acceptance shape: 8 ranks publish through a real
    TCPStore; rank 5 is slow (its p50 shows it), rank 7 never publishes
    (straggler-timeout -> missing, not a hang); counters sum,
    histograms merge elementwise, gauges stay per-rank."""
    from paddle_tpu.distributed import TCPStore

    store = TCPStore("127.0.0.1", 0, world_size=8, is_master=True)
    try:
        regs = {r: _rank_registry(r, straggle=500.0 if r == 5 else 0.0)
                for r in range(8)}
        for r in range(7):        # rank 7 = dead straggler
            aggregate.publish_snapshot(store, r, regs[r])
        t0 = __import__("time").monotonic()
        view = aggregate.fleet_snapshot(
            store=store, world_size=8, rank=0, registry=regs[0],
            timeout=0.2)
        assert __import__("time").monotonic() - t0 < 5.0  # no hang
    finally:
        store.close()
    assert view["missing"] == [7]
    assert view["ranks"] == list(range(7))
    assert view["world_size"] == 8
    # counters sum over the 7 present ranks
    assert view["merged"]["train"]["steps"] == 7 * 8
    # histogram merged elementwise: count is the fleet total and the
    # bucket counts sum to it
    h = view["merged"]["train"]["step_ms"]
    assert h["count"] == 7 * 8
    assert sum(h["counts"]) == h["count"]
    assert h["sum"] == pytest.approx(6 * 8 * 10.0 + 8 * 510.0)
    # gauges keep per-rank identity
    of = view["merged"]["train"]["overlap_frac"]
    assert set(of) == {f"rank={r}" for r in range(7)}
    assert of["rank=5"] == pytest.approx(0.8)
    # skew: the slow rank is attributed, with a positive p50 spread
    skew = view["skew"]
    assert skew["slowest_rank"] == 5
    assert set(skew["p50_ms"]) == set(range(7))
    assert skew["p50_ms"][5] > skew["p50_ms"][0]
    assert skew["p50_spread_ms"] > 0
    assert skew["overlap_frac"][5] == pytest.approx(0.8)
    # phase attribution: rank 5's comm_ms sits far above fleet median
    assert skew["slowest_phase"] == "train.comm_ms"


def test_fleet_snapshot_local_degenerate(metrics_on):
    """No store: the local single-rank view, same shape."""
    reg = _rank_registry(0)
    view = aggregate.fleet_snapshot(registry=reg, rank=0)
    assert view["world_size"] == 1 and view["missing"] == []
    assert view["merged"]["train"]["steps"] == 8
    assert view["skew"]["slowest_rank"] == 0
    assert view["schema_version"] == obs.events.SCHEMA_VERSION


def test_skew_phase_attribution_two_ranks(metrics_on):
    """2-rank regression: the phase reference must exclude the slowest
    rank's own value — with it included, a 2-rank fleet's median IS its
    max, every ratio caps at 1.0 and attribution degenerates to
    declaration order instead of the actual outlier phase."""
    def payload(comm, opt):
        mts = []
        for name, mean in (("train.step_ms", 100.0 + comm),
                           ("train.comm_ms", comm),
                           ("train.opt_step_ms", opt)):
            mts.append({"name": name, "kind": "histogram",
                        "labels": [], "count": 4, "sum": mean * 4,
                        "buckets": list(obs.LATENCY_BUCKETS_MS),
                        "counts": [0] * 9 + [4] + [0] * 18})
        return {"metrics": mts}

    skew = aggregate.derive_skew({0: payload(1.0, 5.0),
                                  1: payload(10.0, 5.0)})
    assert skew["slowest_rank"] == 1
    # comm is 10x the peer; opt is equal — comm must win, not the
    # first _PHASE_HISTS entry
    assert skew["slowest_phase"] == "train.comm_ms"


def test_merge_rejects_mismatched_buckets(metrics_on):
    a = {"metrics": [{"name": "h", "kind": "histogram", "labels": [],
                      "count": 1, "sum": 1.0, "buckets": [1.0, 2.0],
                      "counts": [1, 0, 0]}]}
    b = {"metrics": [{"name": "h", "kind": "histogram", "labels": [],
                      "count": 1, "sum": 1.0, "buckets": [1.0, 3.0],
                      "counts": [1, 0, 0]}]}
    with pytest.raises(ValueError, match="buckets"):
        aggregate.merge_snapshots({0: a, 1: b})


# ==========================================================================
# flight-dump schema v2
# ==========================================================================

def test_flight_dump_schema_v2(tmp_path, metrics_on, monkeypatch):
    monkeypatch.setenv("PDTPU_FLIGHT_DIR", str(tmp_path))
    obs.events.clear()
    obs.emit("k", x=1)
    path = obs.dump("schema_check")
    rec = json.load(open(path))
    assert rec["schema_version"] == obs.events.SCHEMA_VERSION == 2
    assert rec["rank"] == 0                  # PADDLE_TRAINER_ID unset
    assert isinstance(rec["host"], str) and rec["host"]
    # rank follows the launcher env (the multi-rank merge key)
    monkeypatch.setenv("PADDLE_TRAINER_ID", "3")
    rec2 = json.load(open(obs.dump("schema_check_rank")))
    assert rec2["rank"] == 3
    assert obs.last_dump().endswith(os.path.basename(obs.last_dump()))


# ==========================================================================
# metrics-off: everything is a cheap no-op
# ==========================================================================

def test_metrics_off_tracing_and_aggregation_noop(tmp_path):
    old = paddle.get_flags("metrics")["metrics"]
    try:
        paddle.set_flags({"metrics": True})
        obs.events.clear()
        tracing._reset()
        paddle.set_flags({"metrics": False})
        with tracing.span("off", a=1):
            assert tracing.inject() is None
            assert tracing.context_fields() == {}
        assert obs.tail() == []                      # nothing emitted

        @tracing.traced
        def f():
            return 1

        assert f() == 1 and obs.tail() == []
        p = str(tmp_path / "t.json")
        assert tracing.export_trace(p) is None       # no stray files
        assert not os.path.exists(p)
        assert aggregate.fleet_snapshot() == {}

        class _Boom:                                  # store untouched
            def set(self, *a, **k):
                raise AssertionError("store touched with metrics off")
            get = add = set

        assert aggregate.fleet_snapshot(store=_Boom(), world_size=8,
                                        rank=0) == {}
    finally:
        paddle.set_flags({"metrics": old})
        tracing._reset()

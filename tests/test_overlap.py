"""Overlap-scheduled hybrid-parallel training (ISSUE 11).

Gates:
- overlap-scheduled bucketed DP grad sync is BITWISE identical to the
  serialized ``apply_collective_grads`` on a CPU mesh (per-param AND
  fused-flat-grad paths, jax.shard_map fallback included);
- bucket readiness follows the backward walk (last layers first);
- ``no_sync`` pauses the scheduler (gradient accumulation);
- comm_ms / overlap_frac accounting reaches the observability registry;
- the pipeline's pp_overlap_p2p reorder changes the schedule, not the
  values;
- the gpt_3d bench row computes with sane accounting on the CPU mesh.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
import paddle_tpu.nn as nn
from paddle_tpu.core import state as _state


def _net():
    paddle.seed(0)
    return nn.Sequential(nn.Linear(8, 32), nn.GELU(),
                         nn.Linear(32, 32), nn.GELU(),
                         nn.Linear(32, 4))


def _x(seed=0):
    return paddle.to_tensor(np.random.default_rng(seed).normal(
        size=(16, 8)).astype("float32"))


def _grads(dp):
    return [np.asarray(p.grad._read()) for p in dp.parameters()
            if p.grad is not None]


def _run_sync(overlap, bucket_bytes=None, steps=1):
    dp = dist.DataParallel(_net(), overlap_grad_sync=overlap)
    if overlap and bucket_bytes is not None:
        dp._overlap.bucket_bytes = bucket_bytes
    x = _x()
    for _ in range(steps):
        loss = (dp(x) ** 2).mean()
        loss.backward()
        dp.apply_collective_grads()
    return _grads(dp), dp


def test_overlap_bitwise_vs_serialized_per_param():
    """Tiny bucket cap -> one collective per param, dispatched during
    backward; result must be bit-identical to the serialized sync."""
    ref, _ = _run_sync(False)
    got, dp = _run_sync(True, bucket_bytes=1)
    for a, b in zip(ref, got):
        assert np.array_equal(a, b)
    assert dp._last_sync_collectives == 6  # 3 Linears x (w, b)
    acct = dp._overlap.last
    assert acct["buckets"] == 6 and acct["comm_ms"] > 0
    assert 0.0 <= acct["overlap_frac"] <= 1.0


def test_overlap_bitwise_default_bucket():
    """Default 25MB cap -> one bucket for this tiny net (degenerates to
    the serialized schedule, still bitwise)."""
    ref, _ = _run_sync(False)
    got, dp = _run_sync(True)
    for a, b in zip(ref, got):
        assert np.array_equal(a, b)
    assert dp._last_sync_collectives == 1


def test_ready_order_is_backward_walk():
    """Bucket readiness = the order the backward walk finalizes grads:
    the LAST layer's params become ready first (the EagerReducer
    reverse-order rationale)."""
    _, dp = _run_sync(True, bucket_bytes=1)
    order = dp._overlap.last["ready_order"]
    params = [p for p in dp._layers.parameters() if not p.stop_gradient]
    assert sorted(order) == list(range(len(params)))
    # the first finalized param belongs to the last Linear, the final
    # finalized param to the first Linear
    assert order[0] in (len(params) - 2, len(params) - 1)
    assert order[-1] in (0, 1)


def test_overlap_bitwise_with_fused_optimizer():
    """Grads living in the fused optimizer's flat buckets (views):
    overlap sync must stay bitwise vs serialized, and the optimizer
    must keep stepping (parity of the trained weights)."""
    import paddle_tpu.optimizer as opt

    def train(overlap):
        dp = dist.DataParallel(_net(), overlap_grad_sync=overlap)
        if overlap:
            dp._overlap.bucket_bytes = 1
        o = opt.AdamW(learning_rate=1e-2, parameters=dp.parameters())
        x = _x(1)
        for _ in range(3):
            loss = (dp(x) ** 2).mean()
            loss.backward()
            dp.apply_collective_grads()
            o.step()
            o.clear_grad(set_to_zero=True)
        return [np.asarray(p._read()) for p in dp.parameters()]

    ref = train(False)
    got = train(True)
    for a, b in zip(ref, got):
        assert np.array_equal(a, b)


def test_no_sync_pauses_scheduler():
    """Accumulation micro-steps under no_sync must not dispatch bucket
    collectives; the sync after the scope covers the accumulated grad
    and stays bitwise vs the serialized accumulate-then-sync."""
    def run(overlap):
        dp = dist.DataParallel(_net(), overlap_grad_sync=overlap)
        if overlap:
            dp._overlap.bucket_bytes = 1
        with dp.no_sync():
            ((dp(_x(2)) ** 2).mean()).backward()
            if overlap:
                assert not dp._overlap._pending \
                    and not dp._overlap._ready_ids
        ((dp(_x(3)) ** 2).mean()).backward()   # accumulates
        dp.apply_collective_grads()
        return _grads(dp)

    ref = run(False)
    got = run(True)
    for a, b in zip(ref, got):
        assert np.array_equal(a, b)


def test_overlap_metrics_reach_registry():
    from paddle_tpu.observability import metrics as m
    reg = m.registry()
    before = reg.counter("train.bucket_syncs",
                         "bucketed grad-sync collectives issued").value
    _, dp = _run_sync(True, bucket_bytes=1)
    assert reg.counter("train.bucket_syncs", "").value == before + 6
    assert reg.gauge("train.overlap_frac", "").value is not None
    snap = reg.snapshot()
    assert "train" in snap and "comm_ms" in snap["train"]


def test_overlap_flag_default_off():
    dp = dist.DataParallel(_net())
    assert dp._overlap is None  # serialized path untouched by default
    assert _state.get_flag("dp_overlap_grad_sync") is False


# ----------------------------------------------------------- pipeline --
def test_pipeline_p2p_overlap_bitwise(tmp_path):
    """pp_overlap_p2p reorders sends, never values: 1F1B loss and every
    stacked-leaf grad bitwise across the flag."""
    import paddle_tpu.nn.functional as F
    from paddle_tpu.distributed.fleet.pipeline import PipelinedBlocks

    mesh = dist.ProcessMesh(np.arange(8).reshape(4, 2), ["pp", "dp"])

    class Block(nn.Layer):
        def __init__(self, width=16):
            super().__init__()
            self.fc1 = nn.Linear(width, 2 * width)
            self.fc2 = nn.Linear(2 * width, width)

        def forward(self, x):
            return x + self.fc2(F.gelu(self.fc1(x)))

    rng = np.random.default_rng(4)
    x = rng.normal(size=(8, 4, 16)).astype("float32")
    y = rng.normal(size=(8, 4, 16)).astype("float32")

    def loss_fn(out, tgt):
        return ((out - tgt) ** 2).mean()

    def run(flag):
        old = _state.get_flag("pp_overlap_p2p")
        _state.set_flags({"pp_overlap_p2p": flag})
        try:
            paddle.seed(5)
            pipe = PipelinedBlocks(Block, 4, mesh=mesh, pp_axis="pp",
                                   num_microbatches=4)
            loss = pipe.train_batch(paddle.to_tensor(x),
                                    paddle.to_tensor(y), loss_fn,
                                    batch_axes="dp")
            loss.backward()
            grads = [np.asarray(pipe.stacked_parameter(n).grad._read())
                     for n, _ in pipe.template.named_parameters()]
            return float(loss), grads
        finally:
            _state.set_flags({"pp_overlap_p2p": old})

    l_on, g_on = run(True)
    l_off, g_off = run(False)
    assert l_on == l_off
    for a, b in zip(g_on, g_off):
        assert np.array_equal(a, b)


# ----------------------------------------------------------- topology --
def test_topology_process_mesh_bridge():
    from paddle_tpu.distributed.fleet.topology import \
        HybridCommunicateGroup

    hcg = HybridCommunicateGroup(dp_degree=2, pp_degree=2, mp_degree=2)
    mesh = hcg.process_mesh()
    assert mesh.dim_names == ["dp", "pp", "mp"]
    assert mesh.shape == [2, 2, 2]
    # degenerate axes are dropped; explicit selection keeps order
    mesh2 = HybridCommunicateGroup(dp_degree=4,
                                   pp_degree=2).process_mesh()
    assert mesh2.dim_names == ["dp", "pp"]
    g = hcg.get_data_parallel_comm_group()
    assert g.nranks == 2 and g.ranks == [0, 4]


def test_gpt_3d_bench_row_smoke():
    """CPU-mesh accounting smoke of the gpt_3d row: topology recorded,
    scaling + overlap fields present, overlap_frac within [0, 1]."""
    import importlib.util
    import os

    path = os.path.join(os.path.dirname(__file__), os.pardir,
                        "benchmarks", "hybrid_bench.py")
    spec = importlib.util.spec_from_file_location("hybrid_bench_smoke",
                                                  path)
    hb = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(hb)
    from paddle_tpu.models.gpt import GPTConfig

    cfg = GPTConfig(vocab_size=64, hidden_size=16, num_layers=2,
                    num_heads=2, max_seq_len=32, dropout=0.0)
    row = hb._measure_gpt_3d(cfg, dp=2, pp=2, mp=1, batch_per_dp=2,
                             seq=8, num_microbatches=2, steps=1,
                             warmup=1, overlap_steps=1)
    assert row["metric"] == "gpt_3d_train_tokens_per_sec"
    assert row["chips"] == 4
    assert row["topology"]["dp"] == 2 and row["topology"]["pp"] == 2
    assert row["value"] > 0 and row["tokens_per_sec_1dev"] > 0
    assert row["scaling_x"] > 0
    ov = row["overlap"]
    assert ov["buckets"] >= 1 and ov["comm_ms"] > 0
    assert 0.0 <= ov["overlap_frac"] <= 1.0
    assert row["pp_overlap_p2p"] is True

"""Round-3 op tail (VERDICT r2 missing #7): auc, yolo_loss,
generate_proposals, fractional pools, unpool1d/3d, decode_jpeg/read_file,
spectral_norm."""
import io

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F


def _t(x):
    return paddle.to_tensor(np.asarray(x))


def test_max_unpool_1d_3d_roundtrip():
    rng = np.random.default_rng(0)
    x1 = _t(rng.normal(size=(2, 3, 8)).astype("float32"))
    o, m = F.max_pool1d(x1, 2, return_mask=True)
    up = F.max_unpool1d(o, m, 2)
    assert tuple(up.shape) == (2, 3, 8)
    flat = x1.numpy().reshape(2, 3, -1)
    picked = np.take_along_axis(flat, m.numpy().reshape(2, 3, -1), -1)
    np.testing.assert_allclose(picked.reshape(o.shape), o.numpy())

    x3 = _t(rng.normal(size=(1, 2, 4, 4, 4)).astype("float32"))
    o3, m3 = F.max_pool3d(x3, 2, return_mask=True)
    up3 = F.max_unpool3d(o3, m3, 2)
    assert tuple(up3.shape) == (1, 2, 4, 4, 4)
    # every pooled value sits at its recorded position
    flat3 = up3.numpy().reshape(1, 2, -1)
    got = np.take_along_axis(flat3, m3.numpy().reshape(1, 2, -1), -1)
    np.testing.assert_allclose(got.reshape(o3.shape), o3.numpy())


@pytest.mark.parametrize("nd", [2, 3])
def test_fractional_max_pool(nd):
    rng = np.random.default_rng(1)
    shape = (2, 3) + (9, 11, 7)[:nd]
    out_sz = (4, 5, 3)[:nd]
    x = _t(rng.normal(size=shape).astype("float32"))
    fn = F.fractional_max_pool2d if nd == 2 else F.fractional_max_pool3d
    out, idx = fn(x, output_size=out_sz, random_u=0.4, return_mask=True)
    assert tuple(out.shape) == (2, 3) + out_sz
    flat = x.numpy().reshape(2, 3, -1)
    picked = np.take_along_axis(flat, idx.numpy().reshape(2, 3, -1), -1)
    np.testing.assert_allclose(picked.reshape(out.shape), out.numpy())
    # deterministic given random_u
    out2 = fn(x, output_size=out_sz, random_u=0.4)
    np.testing.assert_allclose(out.numpy(), out2.numpy())
    # global max survives pooling (regions tile the input)
    np.testing.assert_allclose(out.numpy().max(), x.numpy().max())


def test_spectral_norm_unit_sigma():
    rng = np.random.default_rng(2)
    w = rng.normal(size=(6, 5)).astype("float32")
    out = F.spectral_norm(_t(w), power_iters=50).numpy()
    # largest singular value of the normalized weight ~ 1
    np.testing.assert_allclose(np.linalg.svd(out)[1][0], 1.0, rtol=1e-3)
    # direction preserved: out proportional to w / sigma
    np.testing.assert_allclose(out, w / np.linalg.svd(w)[1][0], rtol=1e-3,
                               atol=1e-4)
    # layer wrapper
    layer = paddle.nn.SpectralNorm((6, 5), power_iters=50)
    np.testing.assert_allclose(layer(_t(w)).numpy(), out, rtol=1e-5)


def test_spectral_norm_conv_dim():
    rng = np.random.default_rng(3)
    w = rng.normal(size=(4, 3, 2, 2)).astype("float32")
    out = F.spectral_norm(_t(w), dim=1, power_iters=60).numpy()
    mat = out.transpose(1, 0, 2, 3).reshape(3, -1)
    np.testing.assert_allclose(np.linalg.svd(mat)[1][0], 1.0, rtol=1e-2)


def test_read_file_decode_jpeg(tmp_path):
    from PIL import Image

    from paddle_tpu.vision.ops import decode_jpeg, read_file

    # smooth gradient (JPEG is lossy; random noise would not survive)
    gy, gx = np.mgrid[0:10, 0:12]
    arr = np.stack([gy * 20, gx * 20, gy * 10 + gx * 10],
                   axis=-1).astype(np.uint8)
    p = tmp_path / "img.jpg"
    Image.fromarray(arr).save(p, format="JPEG", quality=95)
    raw = read_file(str(p))
    assert raw.dtype == np.uint8 and raw.ndim == 1
    img = decode_jpeg(raw, mode="rgb")
    assert tuple(img.shape) == (3, 10, 12)
    # jpeg is lossy; just require closeness
    assert np.abs(img.numpy().transpose(1, 2, 0).astype(int)
                  - arr.astype(int)).mean() < 12


def test_auc_op():
    from paddle_tpu.ops.special import auc

    # perfectly separable predictions -> AUC 1
    pred = np.array([[0.9, 0.1], [0.8, 0.2], [0.3, 0.7], [0.1, 0.9]],
                    np.float32)
    lab = np.array([[0], [0], [1], [1]], np.int64)
    a, pos, neg = auc(_t(pred), _t(lab))
    np.testing.assert_allclose(float(a), 1.0, atol=1e-6)
    assert int(pos.numpy().sum()) == 2 and int(neg.numpy().sum()) == 2
    # inverted labels -> AUC 0
    a0, _, _ = auc(_t(pred), _t(1 - lab))
    np.testing.assert_allclose(float(a0), 0.0, atol=1e-6)
    # random-ish vs sklearn-style reference on a bigger draw
    rng = np.random.default_rng(5)
    p = rng.uniform(size=400).astype(np.float32)
    y = (rng.uniform(size=400) < p).astype(np.int64)  # correlated
    a2, pos2, neg2 = auc(_t(np.stack([1 - p, p], 1)), _t(y[:, None]))
    # rank-based reference AUC
    order = np.argsort(p)
    ranks = np.empty(400)
    ranks[order] = np.arange(1, 401)
    n_pos, n_neg = y.sum(), (1 - y).sum()
    ref = (ranks[y == 1].sum() - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg)
    np.testing.assert_allclose(float(a2), ref, atol=2e-3)
    # streaming: two halves with stat carry == one shot
    a_h1, p1, n1 = auc(_t(np.stack([1 - p[:200], p[:200]], 1)),
                       _t(y[:200, None]))
    a_h2, p2, n2 = auc(_t(np.stack([1 - p[200:], p[200:]], 1)),
                       _t(y[200:, None]), stat_pos=p1, stat_neg=n1)
    np.testing.assert_allclose(float(a_h2), float(a2), atol=1e-6)


def test_yolo_loss_shapes_and_learning_signal():
    from paddle_tpu.vision.ops import yolo_loss

    rng = np.random.default_rng(6)
    n, c, h, w = 2, 3 * (5 + 4), 5, 5
    x = _t(rng.normal(size=(n, c, h, w)).astype("float32") * 0.1)
    gt_box = np.zeros((n, 3, 4), np.float32)
    gt_box[:, 0] = [0.5, 0.5, 0.3, 0.4]   # one real box per image
    gt_label = np.zeros((n, 3), np.int64)
    loss = yolo_loss(x, _t(gt_box), _t(gt_label),
                     anchors=[10, 13, 16, 30, 33, 23],
                     anchor_mask=[0, 1, 2], class_num=4,
                     ignore_thresh=0.7, downsample_ratio=32)
    assert tuple(loss.shape) == (n,)
    assert np.isfinite(loss.numpy()).all() and (loss.numpy() > 0).all()
    # gradient flows to the head
    xg = _t(rng.normal(size=(n, c, h, w)).astype("float32") * 0.1)
    xg.stop_gradient = False
    l = yolo_loss(xg, _t(gt_box), _t(gt_label),
                  anchors=[10, 13, 16, 30, 33, 23],
                  anchor_mask=[0, 1, 2], class_num=4,
                  ignore_thresh=0.7, downsample_ratio=32)
    l.sum().backward()
    assert np.abs(xg.grad.numpy()).sum() > 0


def test_generate_proposals():
    from paddle_tpu.vision.ops import generate_proposals

    rng = np.random.default_rng(7)
    n, a, h, w = 1, 3, 4, 4
    scores = rng.uniform(size=(n, a, h, w)).astype(np.float32)
    deltas = (rng.normal(size=(n, a * 4, h, w)) * 0.1).astype(np.float32)
    # anchors laid out per (H, W, A)
    anchors = np.zeros((h, w, a, 4), np.float32)
    for i in range(h):
        for j in range(w):
            for k in range(a):
                cx, cy, sz = j * 16 + 8, i * 16 + 8, 8 * (k + 1)
                anchors[i, j, k] = [cx - sz, cy - sz, cx + sz, cy + sz]
    variances = np.ones_like(anchors)
    rois, probs, num = generate_proposals(
        _t(scores), _t(deltas), _t(np.array([[64.0, 64.0]], np.float32)),
        _t(anchors.reshape(-1, 4)), _t(variances.reshape(-1, 4)),
        pre_nms_top_n=30, post_nms_top_n=10, nms_thresh=0.5,
        min_size=2.0, return_rois_num=True)
    r = rois.numpy()
    assert r.shape[1] == 4 and r.shape[0] == int(num.numpy()[0]) > 0
    assert probs.shape[0] == r.shape[0]
    # clipped to the image
    assert (r >= 0).all() and (r[:, 0::2] <= 64).all() \
        and (r[:, 1::2] <= 64).all()
    # scores sorted descending
    pr = probs.numpy().ravel()
    assert (np.diff(pr) <= 1e-6).all()


def test_interpolate_bicubic_mode():
    """VERDICT r2 missing #7 tail: bicubic interpolate produces the
    cubic-kernel result (jax.image 'cubic'), differs from bilinear, and
    reproduces constant + linear ramps exactly away from borders."""
    x = np.zeros((1, 1, 4, 4), np.float32)
    x[0, 0] = np.arange(16).reshape(4, 4)
    t = _t(x)
    cub = F.interpolate(t, size=(8, 8), mode="bicubic",
                        align_corners=False).numpy()
    lin = F.interpolate(t, size=(8, 8), mode="bilinear",
                        align_corners=False).numpy()
    assert cub.shape == (1, 1, 8, 8)
    assert not np.allclose(cub, lin)
    # constant input is reproduced exactly
    const = F.interpolate(_t(np.full((1, 1, 4, 4), 3.25, np.float32)),
                          size=(8, 8), mode="bicubic").numpy()
    np.testing.assert_allclose(const, 3.25, rtol=1e-5)
    # upscale-downscale of a smooth ramp round-trips closely
    back = F.interpolate(_t(cub), size=(4, 4), mode="bicubic").numpy()
    np.testing.assert_allclose(back[0, 0, 1:3, 1:3], x[0, 0, 1:3, 1:3],
                               atol=0.5)


def test_multiclass_nms():
    from paddle_tpu.vision.ops import multiclass_nms

    bboxes = np.array([[[0, 0, 10, 10], [0, 1, 10, 11],
                        [20, 20, 30, 30]]], np.float32)
    scores = np.array([[[0.9, 0.85, 0.1],     # class 0
                        [0.2, 0.3, 0.8]]], np.float32)  # class 1
    out, idx, num = multiclass_nms(
        _t(bboxes), _t(scores), score_threshold=0.15, nms_top_k=10,
        keep_top_k=10, nms_threshold=0.5, return_index=True)
    o = out.numpy()
    assert int(num.numpy()[0]) == o.shape[0]
    got = {(int(r[0]), tuple(r[2:].astype(int))): r[1] for r in o}
    # class 0: near-duplicates suppressed, best kept
    assert (0, (0, 0, 10, 10)) in got
    assert (0, (0, 1, 10, 11)) not in got
    # class 1: box 2 kept (0.8), box 1 kept too (0.3 > 0.15, disjoint)
    assert (1, (20, 20, 30, 30)) in got
    # results sorted by score descending
    assert (np.diff(o[:, 1]) <= 1e-6).all()
    # background_label removes a class entirely
    out2 = multiclass_nms(_t(bboxes), _t(scores), score_threshold=0.15,
                          nms_top_k=10, keep_top_k=10,
                          background_label=0, return_rois_num=False)
    assert (out2.numpy()[:, 0] == 1).all()


def test_adaptive_max_pool_return_mask():
    """Adaptive max pool with indices (reference max_pool2d_with_index
    adaptive mode): values match the maskless path, indices address the
    flat spatial dims."""
    rng = np.random.default_rng(9)
    x = _t(rng.normal(size=(2, 3, 9, 11)).astype("float32"))
    out, idx = F.adaptive_max_pool2d(x, (4, 5), return_mask=True)
    plain = F.adaptive_max_pool2d(x, (4, 5))
    np.testing.assert_allclose(out.numpy(), plain.numpy())
    flat = x.numpy().reshape(2, 3, -1)
    picked = np.take_along_axis(flat, idx.numpy().reshape(2, 3, -1), -1)
    np.testing.assert_allclose(picked.reshape(out.shape), out.numpy())

    x3 = _t(rng.normal(size=(1, 2, 6, 6, 6)).astype("float32"))
    o3, i3 = F.adaptive_max_pool3d(x3, 2, return_mask=True)
    flat3 = x3.numpy().reshape(1, 2, -1)
    picked3 = np.take_along_axis(flat3, i3.numpy().reshape(1, 2, -1), -1)
    np.testing.assert_allclose(picked3.reshape(o3.shape), o3.numpy())

    x1 = _t(rng.normal(size=(2, 3, 10)).astype("float32"))
    o1, i1 = F.adaptive_max_pool1d(x1, 4, return_mask=True)
    flat1 = x1.numpy().reshape(2, 3, -1)
    picked1 = np.take_along_axis(flat1, i1.numpy().reshape(2, 3, -1), -1)
    np.testing.assert_allclose(picked1.reshape(o1.shape), o1.numpy())

"""ISSUE-19 training-perf acceptance: selective remat (bitwise policy
family + static-peak drop + headroom walk), fused residual/norm glue
kernels (kernel-vs-twin bitwise parity fwd AND bwd, model-level wiring),
and the double-buffered input pipeline (bitwise loss trajectory +
overlap metrics).

The remat bitwise contract is a FAMILY property: every checkpoint
policy (``full``, ``dots_saveable``, ..., and the new
``everything_saveable`` remat-OFF anchor that saves every residual and
recomputes nothing) runs the same block math through the same
whole-region ``jax.vjp`` — only saved-vs-recomputed residuals differ,
never the arithmetic — so grads are bitwise-identical across the whole
family.  The eager per-op tape sits OUTSIDE the family (its backward
accumulates cotangents in per-op order, ~1e-10 relative off the
region vjp) and is compared at the test_models.py tolerance instead.
"""
import os
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.models.gpt import GPTBlock, GPTConfig, GPTForCausalLM
from paddle_tpu.models.llama import LlamaConfig, LlamaDecoderLayer

# every non-anchor policy; "full" spells policy=None (recompute all)
_POLICIES = ("full", "dots_saveable", "dots_and_kernels_saveable",
             "transformer_saveable")
_ANCHOR = "everything_saveable"  # save ALL residuals == remat off


def _flag(name):
    return paddle.get_flags(name)[name]


@pytest.fixture()
def metrics_on():
    old = _flag("metrics")
    paddle.set_flags({"metrics": True})
    yield
    paddle.set_flags({"metrics": old})


# ==========================================================================
# selective remat: bitwise across the policy family
# ==========================================================================

def _gpt_cfg(**kw):
    d = dict(vocab_size=64, hidden_size=32, num_layers=1, num_heads=4,
             max_seq_len=16, dropout=0.0)
    d.update(kw)
    return GPTConfig(**d)


def _run_gpt_block(policy):
    paddle.seed(0)
    blk = GPTBlock(_gpt_cfg())
    blk.train()
    blk._recompute = True
    blk._recompute_policy = None if policy == "full" else policy
    x = paddle.to_tensor(np.random.default_rng(0).standard_normal(
        (2, 8, 32)).astype("float32"))
    loss = (blk(x) ** 2).mean()
    loss.backward()
    return float(loss), [p.grad.numpy().copy() for p in blk.parameters()
                         if p.grad is not None]


def _run_llama_layer(policy):
    paddle.seed(0)
    layer = LlamaDecoderLayer(LlamaConfig(
        vocab_size=128, hidden_size=32, num_layers=1, num_heads=4,
        num_kv_heads=2, max_seq_len=32))
    layer.train()
    layer._recompute = True
    layer._policy = None if policy == "full" else policy
    x = paddle.to_tensor(np.random.default_rng(1).standard_normal(
        (2, 8, 32)).astype("float32"))
    loss = (layer(x) ** 2).mean()
    loss.backward()
    return float(loss), [p.grad.numpy().copy()
                         for p in layer.parameters()
                         if p.grad is not None]


def _run_bf16_master(policy):
    """bf16 O2 forward + fp32 master-weight SGD: the mixed-precision
    step stays inside the bitwise family too (grads AND the post-step
    master weights)."""
    import paddle_tpu.amp as amp
    paddle.seed(0)
    blk = GPTBlock(_gpt_cfg())
    blk.train()
    blk._recompute = True
    blk._recompute_policy = None if policy == "full" else policy
    sgd = paddle.optimizer.SGD(0.1, parameters=blk.parameters(),
                               multi_precision=True)
    x = paddle.to_tensor(np.random.default_rng(0).standard_normal(
        (2, 8, 32)).astype("float32"))
    with amp.auto_cast(level="O2", dtype="bfloat16"):
        out = blk(x)
    loss = (out.astype("float32") ** 2).mean()
    loss.backward()
    grads = [p.grad.numpy().copy() for p in blk.parameters()
             if p.grad is not None]
    sgd.step()
    return float(loss), grads + [p.numpy().copy()
                                 for p in blk.parameters()]


@pytest.mark.parametrize("case", ("gpt_block", "llama_layer",
                                  "bf16_master"))
def test_remat_policy_family_bitwise(case):
    """Grads with remat ON (any policy) are BITWISE-identical to the
    everything_saveable anchor (remat off: zero recompute)."""
    run = {"gpt_block": _run_gpt_block, "llama_layer": _run_llama_layer,
           "bf16_master": _run_bf16_master}[case]
    ref_loss, ref_arrs = run(_ANCHOR)
    assert len(ref_arrs) >= 9  # the whole block's parameter set
    for policy in _POLICIES:
        loss, arrs = run(policy)
        assert loss == ref_loss, policy
        assert len(arrs) == len(ref_arrs)
        for i, (a, b) in enumerate(zip(arrs, ref_arrs)):
            assert a.dtype == b.dtype and (a == b).all(), \
                f"{case}/{policy}: array {i} not bitwise"


def test_remat_vs_eager_tape_tolerance():
    """The eager per-op tape (no recompute at all) sits OUTSIDE the
    bitwise family but within the repo's established tolerance
    (test_models.py rtol=1e-4): cotangent accumulation order differs,
    math does not."""
    paddle.seed(0)
    blk = GPTBlock(_gpt_cfg())
    blk.train()
    x = paddle.to_tensor(np.random.default_rng(0).standard_normal(
        (2, 8, 32)).astype("float32"))
    loss = (blk(x) ** 2).mean()
    loss.backward()
    eager = [p.grad.numpy().copy() for p in blk.parameters()
             if p.grad is not None]
    ref_loss, ref = _run_gpt_block(_ANCHOR)
    assert float(loss) == pytest.approx(ref_loss, rel=1e-6)
    for a, b in zip(eager, ref):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6)


def test_to_static_remat_kwarg_and_validation():
    """``jit.to_static(remat=...)`` runs the converted forward under
    the checkpoint policy (value-identical capture; the recompute only
    moves WHAT the backward keeps live); unknown policy names raise at
    decoration instead of silently training without remat."""
    paddle.seed(0)
    cfg = _gpt_cfg(num_layers=2)
    m = GPTForCausalLM(cfg)
    m.train()
    ids = paddle.to_tensor(np.random.default_rng(0).integers(
        0, cfg.vocab_size, (2, 8)).astype(np.int32))
    lab = paddle.to_tensor(np.random.default_rng(1).integers(
        0, cfg.vocab_size, (2, 8)).astype(np.int32))

    def build(**kw):
        @paddle.jit.to_static(full_graph=True, **kw)
        def fwd(i, l):
            return m(i, l)
        return fwd

    plain = build()
    for remat in (True, "full", "dots_and_kernels_saveable"):
        fused = build(remat=remat)
        for _ in range(2):
            assert float(fused(ids, lab)) == float(plain(ids, lab)), \
                remat

    with pytest.raises(ValueError, match="remat"):
        build(remat="not_a_policy")


def test_model_prepare_remat_flags_blocks():
    """``hapi.Model.prepare(remat=...)`` flips every transformer block
    to the recompute path; ``remat=True`` resolves to the default
    policy; a network with no remat-capable blocks warns."""
    cfg = _gpt_cfg(num_layers=2)
    net = GPTForCausalLM(cfg)
    m = paddle.Model(net)
    m.prepare(paddle.optimizer.SGD(0.1, parameters=net.parameters()),
              remat=True)
    blocks = [b for b in net.gpt.blocks]
    assert all(b._recompute for b in blocks)
    assert all(b._recompute_policy == "dots_and_kernels_saveable"
               for b in blocks)

    plain = nn.Sequential(nn.Linear(4, 4))
    m2 = paddle.Model(plain)
    with pytest.warns(RuntimeWarning, match="remat"):
        m2.prepare(paddle.optimizer.SGD(
            0.1, parameters=plain.parameters()), remat=True)


def test_remat_static_peak_drop():
    """The acceptance gauge: on a multi-layer GPT block stack the
    captured train step's ``static_peak_bytes`` drops >= 25% with remat
    on (measured 54% on this geometry, 56% at the full gpt124m
    hidden=768/seq=256/batch=8 shape).  Single-layer stacks can go the
    OTHER way (nothing upstream to free); the saving is a multi-layer
    property, which is why this config has 4 layers."""
    def peak(remat):
        paddle.seed(0)
        cfg = _gpt_cfg(vocab_size=128, hidden_size=256, num_layers=4,
                       num_heads=8, max_seq_len=128,
                       use_flash_attention=False, recompute=remat,
                       recompute_policy="dots_and_kernels_saveable")
        m = GPTForCausalLM(cfg)
        m.train()
        opt = paddle.optimizer.SGD(0.01, parameters=m.parameters())

        @paddle.jit.to_static(full_graph=True)
        def step(i, l):
            loss = m(i, l)
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss

        rng = np.random.default_rng(0)
        ids = paddle.to_tensor(rng.integers(
            0, cfg.vocab_size, (4, 128)).astype(np.int32))
        lab = paddle.to_tensor(rng.integers(
            0, cfg.vocab_size, (4, 128)).astype(np.int32))
        step(ids, lab)
        exe = next(iter(step._cache.values()))
        return int(exe.static_peak_bytes)

    p_off, p_on = peak(False), peak(True)
    assert p_on < 0.75 * p_off, (p_off, p_on)


def test_train_batch_headroom_walk():
    """calibrate.train_batch_headroom walks batch sizes against the
    static-peak gauge: rows are monotone in peak, the fit verdicts
    honor the budget, and remat raises (or holds) max_batch_fits."""
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "benchmarks"))
    import calibrate

    out = calibrate.train_batch_headroom(
        budget_gb=1.0, hidden=64, layers=2, heads=4, vocab=128,
        seq=32, batches=(1, 2, 4))
    rows = out["rows"]
    assert rows and all(r["static_peak_bytes"] > 0 for r in rows)
    peaks = [r["static_peak_bytes"] for r in rows]
    assert peaks == sorted(peaks)
    budget = 1.0 * 2 ** 30
    for r in rows:
        assert r["fits"] == (r["static_peak_bytes"] <= budget)
    assert out["max_batch_fits"] == max(
        (r["batch"] for r in rows if r["fits"]), default=0)


# ==========================================================================
# fused residual/norm glue kernels: twin parity (PR4/PR11/PR18 gate)
# ==========================================================================

_GEOMS = ((256, 128), (100, 96), (40, 64))  # rect, padded, sub-block


def _glue_inputs(n, h, seed, n_arrays):
    rng = np.random.default_rng(seed)
    return [np.asarray(rng.standard_normal((n, h)), np.float32)
            for _ in range(n_arrays)]


@pytest.mark.parametrize("n,h", _GEOMS)
def test_fused_residual_layer_norm_twin_bitwise(n, h):
    from paddle_tpu.ops.pallas import fused_residual_norm as frn
    x, y, dr, g = _glue_inputs(n, h, 0, 4)
    w = np.asarray(np.random.default_rng(1).standard_normal(h),
                   np.float32)
    b = np.asarray(np.random.default_rng(2).standard_normal(h),
                   np.float32)
    rows = 64  # force a multi-block grid on the 256-row geometry
    k = frn.fused_residual_layer_norm_fwd(x, y, w, b, rows=rows,
                                          interpret=True)
    t = frn.fused_residual_layer_norm_fwd_twin(x, y, w, b, rows=rows)
    for kv, tv in zip(k, t):
        assert (np.asarray(kv) == np.asarray(tv)).all()
    res, _, mean, rstd = (np.asarray(v) for v in k)
    kb = frn.fused_residual_layer_norm_bwd(res, w, mean, rstd, dr, g,
                                           rows=rows, interpret=True)
    tb = frn.fused_residual_layer_norm_bwd_twin(res, w, mean, rstd,
                                                dr, g, rows=rows)
    for kv, tv in zip(kb, tb):
        assert (np.asarray(kv) == np.asarray(tv)).all()


@pytest.mark.parametrize("n,h", _GEOMS)
def test_fused_residual_rms_norm_twin_bitwise(n, h):
    from paddle_tpu.ops.pallas import fused_residual_norm as frn
    x, y, dr, g = _glue_inputs(n, h, 3, 4)
    w = np.asarray(np.random.default_rng(4).standard_normal(h),
                   np.float32)
    rows = 64
    k = frn.fused_residual_rms_norm_fwd(x, y, w, rows=rows,
                                        interpret=True)
    t = frn.fused_residual_rms_norm_fwd_twin(x, y, w, rows=rows)
    for kv, tv in zip(k, t):
        assert (np.asarray(kv) == np.asarray(tv)).all()
    res, _, rstd = (np.asarray(v) for v in k)
    kb = frn.fused_residual_rms_norm_bwd(res, w, rstd, dr, g,
                                         rows=rows, interpret=True)
    tb = frn.fused_residual_rms_norm_bwd_twin(res, w, rstd, dr, g,
                                              rows=rows)
    for kv, tv in zip(kb, tb):
        assert (np.asarray(kv) == np.asarray(tv)).all()


def test_fused_glue_grads_match_reference():
    """The custom_vjp backward against jax.grad of an unfused reference
    chain: same residual/norm math, fp32-stat tolerance."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops.pallas import fused_residual_norm as frn

    x, y = (jnp.asarray(a) for a in _glue_inputs(48, 64, 7, 2))
    w = jnp.asarray(np.random.default_rng(8).standard_normal(64),
                    jnp.float32)
    b = jnp.asarray(np.random.default_rng(9).standard_normal(64),
                    jnp.float32)

    def fused(xv, yv, wv, bv):
        r, o = frn.fused_residual_layer_norm(xv, yv, wv, bv,
                                             interpret=True)
        return jnp.sum(r * o)

    def ref(xv, yv, wv, bv):
        r = xv + yv
        r32 = r.astype(jnp.float32)
        mean = jnp.mean(r32, axis=1, keepdims=True)
        var = jnp.mean(jnp.square(r32 - mean), axis=1, keepdims=True)
        o = (r32 - mean) * jax.lax.rsqrt(var + 1e-5) * wv + bv
        return jnp.sum(r * o)

    gf = jax.grad(fused, argnums=(0, 1, 2, 3))(x, y, w, b)
    gr = jax.grad(ref, argnums=(0, 1, 2, 3))(x, y, w, b)
    for a, c in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(c),
                                   rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("family", ("gpt", "llama", "bert"))
def test_glue_fusion_model_parity_and_training(family):
    """Flag-gated model wiring: the glue-fused TRAIN forward matches
    the unfused one to fp32-stat tolerance for all three block styles
    (pre-norm GPT/LLaMA via the pending-branch thread, post-LN BERT in
    place), and grads stay finite under remat+glue composition."""
    def build():
        paddle.seed(0)
        if family == "gpt":
            from paddle_tpu.models.gpt import GPTModel
            m = GPTModel(_gpt_cfg(num_layers=2))
        elif family == "llama":
            from paddle_tpu.models.llama import LlamaModel
            m = LlamaModel(LlamaConfig(
                vocab_size=128, hidden_size=32, num_layers=2,
                num_heads=4, num_kv_heads=2, max_seq_len=32))
        else:
            from paddle_tpu.models.bert import BertConfig, BertModel
            m = BertModel(BertConfig(
                vocab_size=64, hidden_size=32, num_layers=2,
                num_heads=4, max_seq_len=16, dropout=0.0))
        m.train()
        return m

    ids = paddle.to_tensor(np.random.default_rng(0).integers(
        0, 64, (2, 8)).astype(np.int32))
    old = _flag("train_glue_fusion")
    try:
        def first(out):
            return out[0] if isinstance(out, tuple) else out

        paddle.set_flags({"train_glue_fusion": False})
        ref = first(build()(ids))
        paddle.set_flags({"train_glue_fusion": True})
        fused_model = build()
        out = first(fused_model(ids))
        np.testing.assert_allclose(out.numpy(), ref.numpy(),
                                   rtol=1e-4, atol=1e-5)
        # grads flow (and stay finite) through the fused chain
        loss = (out ** 2).mean()
        loss.backward()
        grads = [p.grad.numpy() for p in fused_model.parameters()
                 if p.grad is not None]
        assert len(grads) >= 10
        assert all(np.isfinite(g).all() for g in grads)
    finally:
        paddle.set_flags({"train_glue_fusion": old})


def test_glue_fusion_drops_dispatches():
    """The calibration probe's op-hook count: the fused train forward
    dispatches fewer ops per layer, with the glue subset (add/norm ops)
    down by 2 per layer (4 glue dispatches -> 2)."""
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "benchmarks"))
    import calibrate

    out = calibrate.measure_train_glue_dispatches()
    assert out["fused_per_layer"] < out["unfused_per_layer"]
    assert (out["glue_unfused_per_layer"]
            - out["glue_fused_per_layer"]) >= 2


# ==========================================================================
# async double-buffered input pipeline
# ==========================================================================

class _RegDataset(paddle.io.Dataset):
    """Deterministic regression data (fixed seed, no shuffle in fit)."""

    def __init__(self, n=48, dim=8, seed=0):
        rng = np.random.default_rng(seed)
        self.x = rng.standard_normal((n, dim)).astype("float32")
        self.y = (self.x @ rng.standard_normal(
            (dim, 1)).astype("float32"))

    def __getitem__(self, i):
        return self.x[i], self.y[i]

    def __len__(self):
        return len(self.x)


def _fit_losses(prefetch, window=1, epochs=2):
    old = _flag("train_prefetch")
    paddle.set_flags({"train_prefetch": prefetch})
    try:
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(),
                            nn.Linear(16, 1))
        m = paddle.Model(net)
        m.prepare(paddle.optimizer.SGD(
            0.05, parameters=net.parameters()), nn.loss.MSELoss())
        losses = []

        class Rec(paddle.callbacks.Callback):
            def on_train_batch_end(self, step, logs=None):
                losses.append(logs["loss"])

        m.fit(_RegDataset(), epochs=epochs, batch_size=8,
              shuffle=False, verbose=0, window=window,
              callbacks=[Rec()])
        return losses
    finally:
        paddle.set_flags({"train_prefetch": old})


@pytest.mark.parametrize("window", (1, 3))
def test_prefetch_loss_trajectory_bitwise(window):
    """Double-buffered staging is value-identical: the full loss
    trajectory matches the synchronous path BITWISE, per-batch and
    windowed both."""
    on = _fit_losses(True, window=window)
    off = _fit_losses(False, window=window)
    assert len(on) == len(off) >= 10
    assert on == off


def test_prefetch_overlap_metrics(metrics_on):
    """CPU smoke for the overlap gauges: with prefetch on, some staging
    ran under the step (input_overlap_frac > 0) and the residual wait
    histogram recorded every serve."""
    import paddle_tpu.observability as obs
    losses = _fit_losses(True)
    assert losses  # trained
    snap = obs.registry().snapshot()["train"]
    assert snap["input_overlap_frac"] > 0.0
    assert snap["input_wait_ms"]["count"] >= len(losses)


def test_prefetch_exhausts_loader_exactly():
    """The feed serves every batch exactly once (no double-consume
    from the staged-ahead batch at epoch end)."""
    n_batches = len(_fit_losses(True, epochs=1))
    assert n_batches == 6  # 48 samples / batch_size 8

"""Forward-shape + trainability tests for the round-3 vision model batch
(VERDICT r2 missing #6): densenet, squeezenet, shufflenetv2, inceptionv3,
googlenet, mobilenetv1/v3. Reference test model:
test/legacy_test/test_vision_models.py (forward on random input)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision import models


def _fwd(model, size=64, batch=2):
    x = paddle.to_tensor(np.random.default_rng(0).normal(
        size=(batch, 3, size, size)).astype("float32"))
    model.eval()
    with paddle.no_grad():
        return model(x)


# The two heaviest forward builds are `slow` (tier-1 budget audit,
# PR7: the 870s run was clipping this file and its trailing siblings):
# each family keeps a tier-1 representative — densenet169 for densenet,
# mobilenet_v3_large for v3 — so per-model coverage survives the gate
# and the marked variants still run under ``-m slow``.
@pytest.mark.parametrize("ctor,kw", [
    pytest.param(models.densenet121, {}, marks=pytest.mark.slow),
    (models.densenet169, {}),
    (models.squeezenet1_0, {}),
    (models.squeezenet1_1, {}),
    (models.mobilenet_v1, {"scale": 0.5}),
    pytest.param(models.mobilenet_v3_small, {},
                 marks=pytest.mark.slow),
    (models.mobilenet_v3_large, {}),
    (models.shufflenet_v2_x0_25, {}),
    (models.shufflenet_v2_x1_0, {}),
    (models.shufflenet_v2_swish, {}),
])
def test_forward_shape(ctor, kw):
    paddle.seed(0)
    model = ctor(num_classes=10, **kw)
    out = _fwd(model)
    assert tuple(out.shape) == (2, 10)
    assert np.isfinite(out.numpy()).all()


def test_inception_v3_forward():
    paddle.seed(0)
    model = models.inception_v3(num_classes=7)
    out = _fwd(model, size=299, batch=1)
    assert tuple(out.shape) == (1, 7)


def test_googlenet_aux_heads():
    paddle.seed(0)
    model = models.GoogLeNet(num_classes=6)
    out, aux1, aux2 = _fwd(model, size=96)
    assert tuple(out.shape) == (2, 6)
    assert tuple(aux1.shape) == (2, 6) and tuple(aux2.shape) == (2, 6)


def test_new_models_train_step():
    """One SGD step must run end-to-end (backward through concat/SE/
    shuffle paths) and change the loss."""
    paddle.seed(0)
    model = models.shufflenet_v2_x0_25(num_classes=4)
    model.train()
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=model.parameters())
    x = paddle.to_tensor(np.random.default_rng(1).normal(
        size=(2, 3, 64, 64)).astype("float32"))
    y = paddle.to_tensor(np.array([1, 3], np.int64))
    losses = []
    for _ in range(3):
        loss = paddle.nn.functional.cross_entropy(model(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_pretrained_rejected():
    with pytest.raises(ValueError, match="pretrained"):
        models.densenet121(pretrained=True)


def test_channel_shuffle_roundtrip():
    """shuffle(groups) interleaves: shuffling twice with g and c//g
    restores the original order."""
    from paddle_tpu.vision.models.shufflenetv2 import channel_shuffle
    x = paddle.to_tensor(
        np.arange(2 * 8 * 2 * 2, dtype=np.float32).reshape(2, 8, 2, 2))
    y = channel_shuffle(channel_shuffle(x, 2), 4)
    np.testing.assert_array_equal(y.numpy(), x.numpy())

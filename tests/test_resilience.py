"""Resilience runtime: deterministic fault injection proving every
recovery path — atomic/versioned checkpoints (torn-write fallback),
retry/backoff on transient store/rpc/download failures, the in-graph
non-finite step guard, and preemption -> checkpoint ->
``Model.fit(resume=True)``. Reference pattern: the Paddle elastic
manager + checkpoint manifests (SURVEY D23)."""
import os
import shutil
import time
import warnings

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import resilience as rs
from paddle_tpu.core import errors
from paddle_tpu.resilience import faults, preempt

# tier-1 runs these under JAX_PLATFORMS=cpu (conftest forces the cpu
# backend); `-m resilience` selects just the fault drills
pytestmark = pytest.mark.resilience


@pytest.fixture(autouse=True)
def _clean_harness():
    faults.clear()
    preempt.clear()
    preempt.uninstall()
    yield
    faults.clear()
    preempt.clear()
    preempt.uninstall()


# --------------------------------------------------------------- faults --

def test_fault_spec_grammar():
    rules = faults.parse("store_transient:get*2;torn_write:*step_8*;"
                         "nan_step:6;preempt:10@2")
    assert [(r.site, r.match, r.times, r.at) for r in rules] == [
        ("store_transient", "get", 2, 1),
        ("torn_write", "*step_8*", 1, 1),  # inner * stays a glob
        ("nan_step", "6", 1, 1),
        ("preempt", "10", 1, 2),
    ]


def test_fault_counting_is_deterministic():
    faults.inject("store_transient", "get", times=2, at=2)
    # occurrence 1 doesn't fire; 2 and 3 fire; 4+ exhausted
    assert [faults.check("store_transient", "get") for _ in range(5)] == \
        [False, True, True, False, False]
    # non-matching keys never fire and don't consume occurrences
    assert not faults.check("store_transient", "set")


def test_fault_env_reset(monkeypatch):
    monkeypatch.setenv("PDTPU_FAULTS", "nan_step:3")
    faults.reset()
    assert not faults.check("nan_step", "2")
    assert faults.check("nan_step", "3")
    faults.clear()
    assert not faults.check("nan_step", "3")


# ---------------------------------------------------------------- retry --

def test_retry_transient_then_success():
    calls, delays = [], []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise ConnectionError("transient")
        return "ok"

    assert rs.retry_call(flaky, sleep=delays.append) == "ok"
    assert len(calls) == 3
    assert len(delays) == 2 and delays[1] > delays[0]  # backoff grows


def test_retry_exhaustion_raises_last():
    calls = []

    def dead():
        calls.append(1)
        raise ConnectionError(f"attempt {len(calls)}")

    with pytest.raises(ConnectionError, match="attempt 3"):
        rs.retry_call(dead, max_attempts=3, sleep=lambda s: None)
    assert len(calls) == 3


def test_retry_only_listed_exceptions():
    def boom():
        raise ValueError("permanent")

    with pytest.raises(ValueError):
        rs.retry_call(boom, sleep=lambda s: None)


def test_retry_giveup_and_hook():
    seen = []

    def dead():
        raise ConnectionError("x")

    with pytest.raises(ConnectionError):
        rs.retry_call(dead, max_attempts=5, sleep=lambda s: None,
                      on_retry=lambda e, k: seen.append(k),
                      giveup=lambda e: len(seen) >= 2)
    assert seen == [1, 2]


def test_retry_decorator():
    state = {"n": 0}

    @rs.retry(max_attempts=4, sleep=lambda s: None)
    def fn(inc):
        state["n"] += inc
        if state["n"] < 3:
            raise ConnectionError("again")
        return state["n"]

    assert fn(1) == 3


# --------------------------------------------------------------- atomic --

def test_atomic_write_commits(tmp_path):
    p = tmp_path / "f.bin"
    with rs.atomic_write(p) as f:
        f.write(b"hello")
    assert p.read_bytes() == b"hello"
    assert [n for n in os.listdir(tmp_path) if "tmp" in n] == []


def test_atomic_write_handled_error_leaves_target(tmp_path):
    p = tmp_path / "f.bin"
    p.write_bytes(b"old")
    with pytest.raises(RuntimeError):
        with rs.atomic_write(p) as f:
            f.write(b"partial")
            raise RuntimeError("handled")
    assert p.read_bytes() == b"old"
    assert [n for n in os.listdir(tmp_path) if "tmp" in n] == []


def test_atomic_write_torn_fault_never_touches_target(tmp_path):
    p = tmp_path / "f.bin"
    p.write_bytes(b"old")
    faults.inject("torn_write", "*f.bin")
    with pytest.raises(faults.InjectedCrash):
        with rs.atomic_write(p) as f:
            f.write(b"x" * 100)
    assert p.read_bytes() == b"old"  # destination untouched
    stray = [n for n in os.listdir(tmp_path) if "tmp" in n]
    assert len(stray) == 1  # crash leaves the torn temp, like real death
    assert os.path.getsize(tmp_path / stray[0]) == 50  # torn mid-file


def test_framework_save_is_atomic(tmp_path):
    p = str(tmp_path / "m.pdparams")
    paddle.save({"w": paddle.ones([2, 2])}, p)
    faults.inject("torn_write", "*m.pdparams")
    with pytest.raises(faults.InjectedCrash):
        paddle.save({"w": paddle.zeros([2, 2])}, p)
    # the old file still loads cleanly — no torn pickle under the name
    w = paddle.load(p)["w"]
    np.testing.assert_array_equal(np.asarray(w._read()), np.ones((2, 2)))


# ------------------------------------------------------------ GradScaler --

def test_grad_scaler_state_dict_roundtrip():
    src = paddle.amp.GradScaler(
        enable=True, init_loss_scaling=1024.0, incr_ratio=3.0,
        decr_ratio=0.25, incr_every_n_steps=7, decr_every_n_nan_or_inf=2,
        use_dynamic_loss_scaling=True)
    src._good_steps, src._bad_steps = 5, 1
    dst = paddle.amp.GradScaler(enable=True)  # all-default twin
    dst.set_state_dict(src.state_dict())
    assert dst.state_dict() == src.state_dict()
    # the restored policy actually drives scaling identically
    dst._found_inf = True
    dst._update_scale()
    assert dst.get_init_loss_scaling() == 1024.0 * 0.25


# ----------------------------------------- distributed ckpt coded errors --

def _dist_save(tmp_path):
    import paddle_tpu.distributed as dist
    path = str(tmp_path / "ckpt")
    dist.save_state_dict({"w": paddle.ones([4, 4]),
                          "b": paddle.ones([4])}, path)
    return dist, path


def test_dist_ckpt_missing_key_lists_offenders(tmp_path):
    dist, path = _dist_save(tmp_path)
    with pytest.raises(errors.NotFoundError) as ei:
        dist.load_state_dict({"nope1": paddle.zeros([2]),
                              "nope2": paddle.zeros([2])}, path)
    msg = str(ei.value)
    assert "nope1" in msg and "nope2" in msg and "PDT-E002" in msg
    assert isinstance(ei.value, KeyError)  # back-compat except clause


def test_dist_ckpt_missing_shard_file_is_coded(tmp_path):
    dist, path = _dist_save(tmp_path)
    datafile = [f for f in os.listdir(path) if f.endswith(".npz")][0]
    os.remove(os.path.join(path, datafile))
    with pytest.raises(errors.CheckpointCorruptError) as ei:
        dist.load_state_dict({"w": paddle.zeros([4, 4])}, path)
    assert datafile in str(ei.value) and "'w'" in str(ei.value)
    assert "PDT-E014" in str(ei.value)


def test_dist_ckpt_absent_dir_is_coded(tmp_path):
    import paddle_tpu.distributed as dist
    with pytest.raises(errors.CheckpointNotFoundError):
        dist.load_state_dict({"w": paddle.zeros([2])},
                             str(tmp_path / "nowhere"))


def test_dist_ckpt_lost_manifest_piece_fails_coverage(tmp_path):
    """A rank dying between its data write and its manifest write must
    not validate: the merged manifest's shards no longer cover the
    global shape (the torn-save window on a multi-host pod)."""
    import pickle
    import paddle_tpu.distributed as dist
    mesh = dist.ProcessMesh(np.arange(8), ["dp"])
    w = dist.shard_tensor(paddle.to_tensor(
        np.arange(16, dtype="float32")), mesh, [dist.Shard(0)])
    path = str(tmp_path / "ckpt")
    dist.save_state_dict({"w": w}, path)
    mpath = os.path.join(path, "metadata")
    meta = pickle.load(open(mpath, "rb"))
    # simulate the dead rank: drop half of w's shards from the manifest
    meta.state_dict_metadata["w"] = meta.state_dict_metadata["w"][:4]
    with open(mpath, "wb") as f:
        pickle.dump(meta, f)
    with pytest.raises(errors.CheckpointCorruptError) as ei:
        dist.load_state_dict({"w": paddle.zeros([16])}, path)
    assert "cover" in str(ei.value) and "'w'" in str(ei.value)


def test_dist_ckpt_torn_manifest_is_coded(tmp_path):
    dist, path = _dist_save(tmp_path)
    with open(os.path.join(path, "metadata"), "wb") as f:
        f.write(b"\x80torn")
    with pytest.raises(errors.CheckpointCorruptError):
        dist.load_state_dict({"w": paddle.zeros([4, 4])}, path)


# ---------------------------------------------------- CheckpointManager --

def _mgr_save(mgr, step, val):
    mgr.save({"state": {"v": paddle.to_tensor(
        np.full((3,), float(val), "float32"))}}, step,
        meta={"mark": val})


def test_manager_versions_and_keep_k(tmp_path):
    mgr = rs.CheckpointManager(tmp_path / "ck", keep_last_k=2)
    for s in (10, 20, 30, 40):
        _mgr_save(mgr, s, s)
    assert [(s, ok) for s, _d, ok in mgr.versions()] == [(30, True),
                                                         (40, True)]
    step, objs, meta = mgr.load()
    assert step == 40 and meta == {"mark": 40}
    np.testing.assert_array_equal(
        np.asarray(objs["state"]["v"]._read()), np.full((3,), 40.0))


def test_manager_torn_version_falls_back(tmp_path):
    mgr = rs.CheckpointManager(tmp_path / "ck", keep_last_k=3)
    _mgr_save(mgr, 10, 10)
    faults.inject("torn_write", "*step_20*")
    with pytest.raises(faults.InjectedCrash):
        _mgr_save(mgr, 20, 20)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        step, objs, _meta = mgr.load()
    assert step == 10
    assert any("torn" in str(x.message) for x in w)
    # bitwise: the fallback state is exactly what was committed
    np.testing.assert_array_equal(
        np.asarray(objs["state"]["v"]._read()), np.full((3,), 10.0))
    # the next committed version sweeps the torn debris — no manual
    # cleanup between runs
    _mgr_save(mgr, 20, 21)
    step, objs, _meta = mgr.load()
    assert step == 20
    np.testing.assert_array_equal(
        np.asarray(objs["state"]["v"]._read()), np.full((3,), 21.0))


def test_manager_gc_sweeps_orphaned_tmp_files(tmp_path):
    """ISSUE 15 satellite: keep-last-K GC also sweeps orphaned
    ``atomic_write`` temp files (a crash mid-commit — the injected
    ``torn_write`` included — strands ``.<name>.tmp.<pid>``), age-gated
    so a LIVE writer's seconds-old temp is never touched.  Repeated
    crash/resume cycles must not accumulate garbage the version-level
    GC can't see."""
    mgr = rs.CheckpointManager(tmp_path / "ck", keep_last_k=2,
                               tmp_ttl_s=3600.0)
    _mgr_save(mgr, 10, 10)
    # a real crash mid-save: torn_write leaves the temp behind
    faults.inject("torn_write", "*step_20*")
    with pytest.raises(faults.InjectedCrash):
        _mgr_save(mgr, 20, 20)
    root = str(tmp_path / "ck")

    def tmps():
        out = []
        for d, _dirs, names in os.walk(root):
            out += [os.path.join(d, n) for n in names
                    if n.startswith(".") and ".tmp." in n]
        return out

    orphans = tmps()
    assert orphans, "torn_write should strand a temp file"
    # age the orphans past the TTL; plant a FRESH one (another process
    # mid-save into the same root) that must survive the sweep
    old = time.time() - 7200
    for p in orphans:
        os.utime(p, (old, old))
    fresh = os.path.join(root, ".live.pdparams.tmp.99999")
    with open(fresh, "wb") as f:
        f.write(b"x")
    _mgr_save(mgr, 30, 30)  # save -> gc -> sweep
    left = tmps()
    assert fresh in left, "a fresh temp (live writer) was swept"
    assert left == [fresh], f"aged orphans survived: {left}"
    # crash/resume cycles stay garbage-free: another torn attempt, aged,
    # swept by the next complete version
    faults.inject("torn_write", "*step_40*")
    with pytest.raises(faults.InjectedCrash):
        _mgr_save(mgr, 40, 40)
    for p in tmps():
        if p != fresh:
            os.utime(p, (old, old))
    _mgr_save(mgr, 50, 50)
    assert tmps() == [fresh]
    step, _objs, _meta = mgr.load()
    assert step == 50


def test_manager_explicit_step_and_empty(tmp_path):
    mgr = rs.CheckpointManager(tmp_path / "ck")
    with pytest.raises(errors.CheckpointNotFoundError):
        mgr.load()
    _mgr_save(mgr, 5, 5)
    step, _objs, _meta = mgr.load(step=5)
    assert step == 5
    with pytest.raises(errors.CheckpointNotFoundError):
        mgr.load(step=99)


# -------------------------------------------------------------- StepGuard --

class _LinReg(paddle.io.Dataset):
    def __init__(self, n=8):
        rng = np.random.default_rng(0)
        self.x = rng.normal(size=(n, 4)).astype("float32")
        self.y = (self.x @ np.arange(1, 5, dtype="float32"))[:, None]

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return self.x[i], self.y[i]


def _model(guard=True, lr=0.01):
    paddle.seed(7)
    net = paddle.nn.Linear(4, 1)
    m = paddle.Model(net)
    opt = paddle.optimizer.Adam(parameters=net.parameters(),
                                learning_rate=lr)
    m.prepare(opt, paddle.nn.MSELoss(), step_guard=guard)
    return m


def _weights(m):
    return {k: np.asarray(v._read())
            for k, v in m.network.state_dict().items()}


def _opt_state(m):
    return {f"{name}.{pid}": np.asarray(t._read())
            for name, store in m._optimizer._accumulators.items()
            for pid, t in store.items()}


def test_step_guard_skip_is_bitwise_noop():
    ds = _LinReg()
    m = _model()
    for i in range(2):  # step 0 eager (discovery), step 1 compiled
        m.train_batch([ds.x[2 * i:2 * i + 2]], [ds.y[2 * i:2 * i + 2]])
    before_w, before_o = _weights(m), _opt_state(m)
    bad = np.full((2, 4), np.nan, "float32")
    m.train_batch([bad], [ds.y[4:6]])
    assert m._step_guard.last_skipped and m._step_guard.bad_streak == 1
    after_w, after_o = _weights(m), _opt_state(m)
    for k in before_w:
        np.testing.assert_array_equal(before_w[k], after_w[k])
    for k in before_o:
        np.testing.assert_array_equal(before_o[k], after_o[k])
    # a good step then trains normally and resets the streak
    m.train_batch([ds.x[4:6]], [ds.y[4:6]])
    assert m._step_guard.bad_streak == 0
    assert not all(np.array_equal(before_w[k], _weights(m)[k])
                   for k in before_w)


def test_step_guard_first_ever_step_bad():
    """A NaN on the very first optimizer step (accumulators born inside
    the guarded step) must also be a clean skip."""
    ds = _LinReg()
    m = _model()
    before = _weights(m)
    m.train_batch([np.full((2, 4), np.nan, "float32")], [ds.y[:2]])
    assert m._step_guard.last_skipped
    for k in before:
        np.testing.assert_array_equal(before[k], _weights(m)[k])
    for arr in _opt_state(m).values():
        assert np.all(np.isfinite(arr))


def test_step_guard_budget_raises_coded():
    ds = _LinReg()
    m = _model()
    m._step_guard.max_bad_steps = 2
    bad = np.full((2, 4), np.nan, "float32")
    with pytest.raises(errors.NonFiniteStepError) as ei:
        for _ in range(5):
            m.train_batch([bad], [ds.y[:2]])
    assert "PDT-E013" in str(ei.value)
    assert ei.value.error_code == "PDT-E013"
    # every skipped step left the params finite
    assert all(np.all(np.isfinite(v)) for v in _weights(m).values())


def test_step_guard_detects_grad_only_nan():
    """Finite loss + non-finite grads (bf16 backward overflow shape):
    the loss scalar looks healthy, so detection rides the periodic
    device-streak sync — without it the guard would skip forever in
    silence."""
    paddle.seed(0)
    layer = paddle.nn.Linear(2, 1)
    opt = paddle.optimizer.SGD(parameters=layer.parameters(),
                               learning_rate=0.1)
    guard = rs.StepGuard(max_bad_steps=2, grad_sync_every=1)
    before = {k: np.asarray(v._read())
              for k, v in layer.state_dict().items()}
    healthy_loss = 1.0
    with pytest.raises(errors.NonFiniteStepError):
        for _ in range(5):
            for p in layer.parameters():
                p.grad = paddle.to_tensor(
                    np.full(p.shape, np.nan, "float32"))
            guard.guarded_step(opt, paddle.to_tensor(healthy_loss))
            opt.clear_grad()
            guard.observe(healthy_loss)
    # every skipped step was a no-op: params never moved
    for k, v in layer.state_dict().items():
        np.testing.assert_array_equal(before[k], np.asarray(v._read()))


def test_step_guard_backs_off_scaler():
    scaler = paddle.amp.GradScaler(enable=True, init_loss_scaling=1024.0,
                                   decr_ratio=0.5,
                                   decr_every_n_nan_or_inf=1)
    guard = rs.StepGuard(max_bad_steps=5, scaler=scaler)
    guard.observe(float("nan"))
    assert scaler.get_init_loss_scaling() == 512.0
    guard.observe(1.0)  # good step resets the streak
    assert guard._host_streak == 0


# ------------------------------------------------- store/rpc/hub retries --

def test_store_ops_retry_transients():
    from paddle_tpu.distributed.store import TCPStore
    store = TCPStore("127.0.0.1", 0, is_master=True, timeout=5)
    try:
        rule = faults.inject("store_transient", "set", times=2)
        store.set("k", b"v")  # two injected failures, then success
        assert rule.fired == 2
        assert store.get("k", timeout=5) == b"v"
        rule = faults.inject("store_transient", "get", times=2)
        assert store.get("k", timeout=5) == b"v"
        assert rule.fired == 2
    finally:
        faults.clear()
        store.close()


def test_store_retry_exhaustion_raises():
    from paddle_tpu.distributed.store import TCPStore
    store = TCPStore("127.0.0.1", 0, is_master=True, timeout=5)
    try:
        faults.inject("store_transient", "add", times=0)  # every attempt
        with pytest.raises(ConnectionError):
            store.add("ctr", 1)
    finally:
        faults.clear()
        store.close()


def test_store_add_never_retries_in_flight_failures(monkeypatch):
    """An ADD whose reply is lost AFTER the server may have applied it
    must NOT be resent — at-least-once ADD double-counts a barrier
    arrival, releasing the barrier early and desyncing every later
    generation. Pre-send failures (fault injection, reconnect) still
    retry; idempotent SET retries through in-flight failures."""
    from paddle_tpu.distributed import store as store_mod
    st = store_mod.TCPStore("127.0.0.1", 0, is_master=True, timeout=5)
    try:
        real = store_mod._store_request
        state = {"fail": 1}

        def flaky(sock, op, key, payload=b""):
            if state["fail"] > 0:
                state["fail"] -= 1
                raise ConnectionResetError("reply lost in flight")
            return real(sock, op, key, payload)

        monkeypatch.setattr(store_mod, "_store_request", flaky)
        with pytest.raises(ConnectionError):
            st.add("ctr", 1)  # in-flight failure: no resend
        state["fail"] = 1
        st.set("k", b"v")  # idempotent: retried through
        monkeypatch.setattr(store_mod, "_store_request", real)
        assert st.get("k", timeout=5) == b"v"
        assert st.add("ctr2", 1) == 1  # the failed add was NOT applied twice
    finally:
        st.close()


def test_rpc_connect_retries_transients():
    from paddle_tpu.distributed import rpc
    rpc.init_rpc("solo", rank=0, world_size=1)
    try:
        rule = faults.inject("rpc_transient", "solo", times=2)
        assert rpc.rpc_sync("solo", divmod, args=(7, 3)) == (2, 1)
        assert rule.fired == 2
    finally:
        faults.clear()
        rpc.shutdown()


def test_hub_download_retries_and_commits_atomically(tmp_path):
    calls = []

    def fetcher(url):
        calls.append(url)
        return b"payload"

    dst = str(tmp_path / "weights.bin")
    faults.inject("download_transient", "weights.bin", times=2)
    paddle.hapi.hub.download("http://x/weights.bin", dst, fetcher=fetcher)
    assert open(dst, "rb").read() == b"payload"
    assert len(calls) == 1  # injected failures happen before the fetch

    faults.clear()
    faults.inject("download_transient", "weights.bin", times=0)
    with pytest.raises(ConnectionError):
        paddle.hapi.hub.download("http://x/weights.bin", dst,
                                 fetcher=fetcher)
    assert open(dst, "rb").read() == b"payload"  # old file intact


# ------------------------------------------------------------- preempt --

def test_preempt_flag_roundtrip():
    import signal as _signal
    assert preempt.install() is True
    try:
        assert preempt.install() is False  # second install doesn't own
        assert not preempt.requested()
        _signal.raise_signal(_signal.SIGTERM)
        assert preempt.requested()
        preempt.clear()
        assert not preempt.requested()
    finally:
        preempt.uninstall()


def test_fit_preserves_user_preempt_scope(tmp_path):
    """fit inside a user's own preempt.install() scope must neither
    clear a pending request nor uninstall the user's handler."""
    import signal as _signal
    ds = _LinReg()
    assert preempt.install() is True
    try:
        _signal.raise_signal(_signal.SIGTERM)  # pending BEFORE fit
        m = _model()
        m.fit(ds, batch_size=2, epochs=2, shuffle=False, verbose=0,
              save_dir=str(tmp_path / "ck"))
        # the pending request was honored at the first step boundary
        assert m._preempted
        step, _objs, meta = rs.CheckpointManager(
            str(tmp_path / "ck")).load()
        assert step == 1 and meta["steps_done"] == 1
        # and fit did not tear down the user's handler
        assert preempt.install() is False  # still installed
        preempt.clear()
        _signal.raise_signal(_signal.SIGTERM)
        assert preempt.requested()  # user's scope still works
    finally:
        preempt.uninstall()


# ------------------------------------------------------- e2e acceptance --

def test_windowed_fit_nan_step_fires_once_at_right_step():
    """The windowed path must count nan_step occurrences exactly like
    the per-batch path: once per EXECUTED step, at execution time."""
    ds = _LinReg()
    rule = faults.inject("nan_step", "3")
    losses = []

    class Spy(paddle.hapi.callbacks.Callback):
        def on_train_batch_end(self, step, logs=None):
            losses.append((logs or {}).get("loss"))

    m = _model()
    m.fit(ds, batch_size=2, epochs=2, shuffle=False, verbose=0,
          window=2, callbacks=[Spy()])
    assert rule.fired == 1
    bad = [i for i, l in enumerate(losses) if l is not None
           and not np.isfinite(l)]
    assert bad == [2]  # global step 3 (0-based index 2), exactly once


def test_model_checkpoint_keep_last_survives_restart(tmp_path):
    """ModelCheckpoint(keep_last=K) must count a previous attempt's
    epoch saves (preemption restart) toward K, not let the directory
    grow unboundedly across restarts."""
    ds = _LinReg()
    ckdir = str(tmp_path / "ck")
    cb = paddle.hapi.callbacks.ModelCheckpoint(1, ckdir, keep_last=2)
    m = _model()
    m.fit(ds, batch_size=2, epochs=3, shuffle=False, verbose=0,
          callbacks=[cb])
    # "restart": a fresh callback instance over the same directory
    cb2 = paddle.hapi.callbacks.ModelCheckpoint(1, ckdir, keep_last=2)
    m2 = _model()
    m2.fit(ds, batch_size=2, epochs=3, shuffle=False, verbose=0,
           callbacks=[cb2])
    kept = sorted(f for f in os.listdir(ckdir)
                  if f.endswith(".pdparams") and f[0].isdigit())
    assert kept == ["1.pdparams", "2.pdparams"]


def test_mid_epoch_resume_with_shuffle_warns(tmp_path):
    ds = _LinReg()
    ckdir = str(tmp_path / "ck")
    faults.inject("preempt", "3")  # mid-epoch
    m = _model()
    m.fit(ds, batch_size=2, epochs=2, shuffle=False, verbose=0,
          save_dir=ckdir)
    preempt.clear()
    m2 = _model()
    with pytest.warns(RuntimeWarning, match="fast-forwarding"):
        m2.fit(ds, batch_size=2, epochs=2, shuffle=True, verbose=0,
               save_dir=ckdir, resume=True)


def test_num_iters_cut_epoch_records_no_false_boundary(tmp_path):
    """An epoch cut short by num_iters must not write an 'epoch
    complete' (epoch+1, 0) version — resume would silently skip the
    epoch's untrained remainder."""
    ds = _LinReg()
    ckdir = str(tmp_path / "ck")
    m = _model()
    m.fit(ds, batch_size=2, epochs=2, shuffle=False, verbose=0,
          save_dir=ckdir, num_iters=6)  # epoch 1 stops at step 2 of 4
    mgr = rs.CheckpointManager(ckdir)
    assert [s for s, _d, _ok in mgr.versions()] == [4]  # epoch 0 only
    _step, _objs, meta = mgr.load()
    assert meta == {"epoch": 1, "steps_done": 0, "global_step": 4}


def test_fit_resume_without_checkpoint_trains_from_scratch(tmp_path):
    ds = _LinReg()
    m = _model()
    m.fit(ds, batch_size=2, epochs=1, shuffle=False, verbose=0,
          save_dir=str(tmp_path / "ck"), resume=True)
    assert rs.CheckpointManager(str(tmp_path / "ck")).latest_complete()


def test_faulted_run_resumes_and_matches_unfaulted(tmp_path):
    """The acceptance drill: checkpoint write killed mid-file, two
    transient store failures, one NaN step, then SIGTERM — the run
    completes via ``fit(resume=True)`` from the newest COMPLETE version
    and matches the unfaulted run, with no manual cleanup between
    attempts. (The step re-executed right after each restart runs as
    the jit discovery pass — eager — while the unfaulted run executes
    it compiled, so final equality is to fused-arithmetic tolerance;
    the restore itself is asserted bitwise.)"""
    ds = _LinReg()
    ckdir = str(tmp_path / "ck")

    # reference: 3 epochs x 4 steps, the same NaN step skipped in-graph
    faults.inject("nan_step", "6")
    ref = _model()
    ref.fit(ds, batch_size=2, epochs=3, shuffle=False, verbose=0)
    ref_w = _weights(ref)
    faults.clear()

    # two transient store failures survived mid-drill via retry/backoff
    from paddle_tpu.distributed.store import TCPStore
    store = TCPStore("127.0.0.1", 0, is_master=True, timeout=5)
    rule = faults.inject("store_transient", "set", times=2)
    store.set("drill/progress", b"attempt-1")
    assert rule.fired == 2
    store.close()
    faults.clear()

    # attempt 1: NaN at step 6 (skipped), then the epoch-1 checkpoint
    # write (version step_8) dies mid-file
    faults.inject("nan_step", "6")
    faults.inject("torn_write", "*step_8*")
    m = _model()
    with pytest.raises(faults.InjectedCrash):
        m.fit(ds, batch_size=2, epochs=3, shuffle=False, verbose=0,
              save_dir=ckdir)
    mgr = rs.CheckpointManager(ckdir)
    assert [(s, ok) for s, _d, ok in mgr.versions()] == [(4, True),
                                                         (8, False)]
    faults.clear()

    # attempt 2 ("new process"): resume auto-falls back to step_4, the
    # re-run NaN step is skipped again, SIGTERM lands at step 10 ->
    # checkpoint-on-preempt + clean exit
    faults.inject("nan_step", "6")
    faults.inject("preempt", "10")
    m = _model()
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        m.fit(ds, batch_size=2, epochs=3, shuffle=False, verbose=0,
              save_dir=ckdir, resume=True)
    assert any("torn" in str(x.message) for x in w)  # fallback happened
    assert m._preempted
    assert mgr.latest_complete()[0] == 10
    faults.clear()
    preempt.clear()

    # attempt 3: resume finishes the run
    m = _model()
    m.fit(ds, batch_size=2, epochs=3, shuffle=False, verbose=0,
          save_dir=ckdir, resume=True)
    assert not m._preempted
    fin_w = _weights(m)

    # the final checkpoint restores BITWISE what is in memory
    step, objs, _meta = mgr.load()
    assert step == 12
    for k, v in objs["model"].items():
        np.testing.assert_array_equal(np.asarray(v._read()), fin_w[k])

    # and the faulted run landed where the unfaulted one did
    for k in ref_w:
        np.testing.assert_allclose(fin_w[k], ref_w[k], rtol=1e-5,
                                   atol=1e-7)


def test_preempted_fit_saves_exact_position(tmp_path):
    ds = _LinReg()
    ckdir = str(tmp_path / "ck")
    faults.inject("preempt", "3")  # mid-epoch (4 steps per epoch)
    m = _model()
    m.fit(ds, batch_size=2, epochs=2, shuffle=False, verbose=0,
          save_dir=ckdir)
    assert m._preempted
    mgr = rs.CheckpointManager(ckdir)
    # exactly ONE checkpoint per preemption, at the exact position
    assert [s for s, _d, _ok in mgr.versions()] == [3]
    step, _objs, meta = mgr.load()
    assert step == 3
    assert meta == {"epoch": 0, "steps_done": 3, "global_step": 3}
    preempt.clear()
    # resume skips exactly the done steps and completes
    m2 = _model()
    m2.fit(ds, batch_size=2, epochs=2, shuffle=False, verbose=0,
           save_dir=ckdir, resume=True)
    assert rs.CheckpointManager(ckdir).latest_complete()[0] == 8


def test_preempt_at_epoch_boundary_does_not_replay_epoch_end(tmp_path):
    """Preemption on the LAST step of an epoch records (epoch+1, 0), so
    the resumed run neither re-runs on_epoch_end with empty logs nor
    re-saves/evaluates for the finished epoch."""
    ds = _LinReg()
    ckdir = str(tmp_path / "ck")
    faults.inject("preempt", "4")  # == steps per epoch
    m = _model()
    m.fit(ds, batch_size=2, epochs=2, shuffle=False, verbose=0,
          save_dir=ckdir)
    _step, _objs, meta = rs.CheckpointManager(ckdir).load()
    assert meta == {"epoch": 1, "steps_done": 0, "global_step": 4}
    preempt.clear()

    epoch_ends = []

    class Spy(paddle.hapi.callbacks.Callback):
        def on_epoch_end(self, epoch, logs=None):
            epoch_ends.append((epoch, dict(logs or {})))

    m2 = _model()
    m2.fit(ds, batch_size=2, epochs=2, shuffle=False, verbose=0,
           save_dir=ckdir, resume=True, callbacks=[Spy()])
    # only epoch 1 runs — epoch 0's boundary is not replayed
    assert [e for e, _l in epoch_ends] == [1]
    assert all("loss" in l for _e, l in epoch_ends)


def test_fit_checkpoint_retention(tmp_path):
    ds = _LinReg()
    ckdir = str(tmp_path / "ck")
    m = _model()
    m.fit(ds, batch_size=2, epochs=5, shuffle=False, verbose=0,
          save_dir=ckdir, keep_last_k=2,
          callbacks=[paddle.hapi.callbacks.ModelCheckpoint(
              1, ckdir, keep_last=2)])
    # keep_last_k bounds the resilience versions; epoch files are
    # unbounded by DEFAULT (no silent deletion of user checkpoints) —
    # here bounded via the explicit opt-in ModelCheckpoint(keep_last=2)
    assert [s for s, _d, _ok in
            rs.CheckpointManager(ckdir).versions()] == [16, 20]
    epoch_files = sorted(f for f in os.listdir(ckdir)
                         if f.endswith(".pdparams")
                         and f[0].isdigit())
    assert epoch_files == ["3.pdparams", "4.pdparams"]
    assert os.path.exists(os.path.join(ckdir, "final.pdparams"))
    # default path: every epoch file kept
    ck2 = str(tmp_path / "ck2")
    m2 = _model()
    m2.fit(ds, batch_size=2, epochs=5, shuffle=False, verbose=0,
           save_dir=ck2, keep_last_k=2)
    kept = sorted(f for f in os.listdir(ck2)
                  if f.endswith(".pdparams") and f[0].isdigit())
    assert kept == [f"{e}.pdparams" for e in range(5)]


def test_mid_epoch_preempt_skips_epoch_boundary(tmp_path):
    """A mid-epoch preemption must exit fast: no on_epoch_end (which
    would mislabel partial weights as the completed epoch via
    ModelCheckpoint) and no eval pass eating the grace period."""
    ds = _LinReg()
    ckdir = str(tmp_path / "ck")
    events = []

    class Spy(paddle.hapi.callbacks.Callback):
        def on_epoch_end(self, epoch, logs=None):
            events.append(("epoch_end", epoch))

        def on_eval_begin(self, logs=None):
            events.append(("eval", None))

        def on_train_end(self, logs=None):
            events.append(("train_end", None))

    faults.inject("preempt", "2")  # mid-epoch (4 steps per epoch)
    m = _model()
    m.fit(ds, eval_data=ds, batch_size=2, epochs=2, shuffle=False,
          verbose=0, save_dir=ckdir, callbacks=[Spy()])
    assert m._preempted
    assert events == []  # no boundary callbacks, eval, or train-end
    assert not os.path.exists(os.path.join(ckdir, "0.pdparams"))
    # no half-trained weights labeled 'final'
    assert not os.path.exists(os.path.join(ckdir, "final.pdparams"))
    # fit owned the handler: the honored request doesn't leak to the
    # next preempt.install() scope in this process
    assert not preempt.requested()

    # boundary preemption DOES run the completed epoch's callbacks
    # (but still skips eval)
    events.clear()
    faults.inject("preempt", "4")
    m2 = _model()
    m2.fit(ds, eval_data=ds, batch_size=2, epochs=2, shuffle=False,
           verbose=0, save_dir=str(tmp_path / "ck2"), callbacks=[Spy()])
    assert events == [("epoch_end", 0)]


def test_sigint_checkpoints_then_propagates(tmp_path):
    """Ctrl-C keeps abort semantics: the position is checkpointed, then
    KeyboardInterrupt propagates — code after fit() must not run on a
    half-trained model believing it completed."""
    import signal as _signal
    ds = _LinReg()
    ckdir = str(tmp_path / "ck")
    fired = []

    class Interrupter(paddle.hapi.callbacks.Callback):
        def on_train_batch_end(self, step, logs=None):
            if not fired and step == 1:
                fired.append(step)
                _signal.raise_signal(_signal.SIGINT)

    m = _model()
    with pytest.raises(KeyboardInterrupt):
        m.fit(ds, batch_size=2, epochs=2, shuffle=False, verbose=0,
              save_dir=ckdir, callbacks=[Interrupter()])
    assert m.preempted  # public indicator
    _step, _objs, meta = rs.CheckpointManager(ckdir).load()
    assert meta["steps_done"] == 2  # checkpointed BEFORE propagating
    assert not preempt.requested()


def test_accumulation_preempt_honored_at_update_boundary(tmp_path):
    """Preemption mid-accumulation must wait for the next optimizer
    update — a checkpoint between micro-batches would silently drop the
    partially summed gradients."""
    ds = _LinReg()
    ckdir = str(tmp_path / "ck")
    faults.inject("preempt", "1")  # micro-batch 1 of a 2-batch window
    m = _model()
    m.fit(ds, batch_size=2, epochs=2, shuffle=False, verbose=0,
          save_dir=ckdir, accumulate_grad_batches=2)
    assert m._preempted
    _step, _objs, meta = rs.CheckpointManager(ckdir).load()
    # honored at the update boundary (global step 2), not at step 1
    assert meta["global_step"] == 2 and meta["steps_done"] == 2


def test_hub_download_retries_mid_body_drops(tmp_path):
    """IncompleteRead (connection dropped mid-body) is not an OSError
    but IS the flaky-store failure retry exists for."""
    import http.client
    calls = []

    def fetcher(url):
        calls.append(url)
        if len(calls) < 3:
            raise http.client.IncompleteRead(b"partial")
        return b"whole"

    dst = str(tmp_path / "w.bin")
    paddle.hapi.hub.download("http://x/w.bin", dst, fetcher=fetcher)
    assert open(dst, "rb").read() == b"whole" and len(calls) == 3


def test_hub_download_gives_up_on_permanent_http_error(tmp_path):
    class Fake404(OSError):
        code = 404

    calls = []

    def fetcher(url):
        calls.append(url)
        raise Fake404("not found")

    with pytest.raises(Fake404):
        paddle.hapi.hub.download("http://x/nope.bin",
                                 str(tmp_path / "nope.bin"),
                                 fetcher=fetcher)
    assert len(calls) == 1  # permanent: no pointless retries

"""Parameter-server mode (SURVEY D9/D24): dense/sparse tables with
server-side accessors, sync + async semantics, the SparseEmbedding
worker layer, and the fleet PS role flow. Servers run in threads (they
are pure-Python TCP services); a subprocess test proves the role env
contract end to end."""
import os
import subprocess
import sys
import textwrap
import threading

import numpy as np
import pytest

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

import paddle_tpu as paddle
from paddle_tpu.distributed.ps import (PsClient, PsServer, PSOptimizer,
                                       SparseEmbedding)


@pytest.fixture()
def cluster():
    servers = [PsServer("127.0.0.1:0", n_workers=1).start()
               for _ in range(2)]
    client = PsClient([f"127.0.0.1:{s.port}" for s in servers])
    yield servers, client
    client.stop_servers()
    client.close()


def test_dense_table_sgd(cluster):
    _, client = cluster
    client.create_dense_table("w", (3,), rule="sgd", lr=0.1)
    client.init_dense("w", np.ones(3, np.float32))
    client.push_dense("w", np.full(3, 2.0, np.float32))
    value, version = client.pull_dense("w")
    np.testing.assert_allclose(value, 1.0 - 0.1 * 2.0)
    assert version == 1


def test_dense_table_adam_matches_local(cluster):
    _, client = cluster
    client.create_dense_table("w", (4,), rule="adam", lr=0.01)
    w0 = np.arange(4, dtype=np.float32)
    client.init_dense("w", w0)
    g = np.full(4, 0.5, np.float32)
    for _ in range(3):
        client.push_dense("w", g)
    value, _ = client.pull_dense("w")
    # local adam reference
    m = v = np.zeros(4, np.float32)
    w = w0.copy()
    for t in range(1, 4):
        m = 0.9 * m + 0.1 * g
        v = 0.999 * v + 0.001 * g * g
        w = w - 0.01 * (m / (1 - 0.9 ** t)) / (
            np.sqrt(v / (1 - 0.999 ** t)) + 1e-8)
    np.testing.assert_allclose(value, w, rtol=1e-6)


def test_sparse_rows_shard_across_servers(cluster):
    servers, client = cluster
    client.create_sparse_table("emb", 4, rule="sgd", lr=1.0)
    ids = np.array([0, 1, 2, 3, 7, 8])
    rows = client.pull_sparse("emb", ids)
    assert rows.shape == (6, 4)
    # rows shard id % 2 across the two server nodes
    assert set(servers[0]._sparse["emb"].rows) == {0, 2, 8}
    assert set(servers[1]._sparse["emb"].rows) == {1, 3, 7}
    # push a grad of 1 to every row: value drops by lr * 1
    client.push_sparse("emb", ids, np.ones((6, 4), np.float32))
    rows2 = client.pull_sparse("emb", ids)
    np.testing.assert_allclose(rows2, rows - 1.0, atol=1e-6)
    # duplicate id pull returns consistent rows
    r = client.pull_sparse("emb", np.array([5, 5]))
    np.testing.assert_allclose(r[0], r[1])


def test_sync_mode_waits_for_all_workers():
    server = PsServer("127.0.0.1:0", n_workers=2, sync=True).start()
    c1 = PsClient([f"127.0.0.1:{server.port}"])
    c2 = PsClient([f"127.0.0.1:{server.port}"])
    c1.create_dense_table("w", (2,), rule="sgd", lr=0.5)
    c1.init_dense("w", np.zeros(2, np.float32))

    v1 = c1.push_dense("w", np.ones(2, np.float32))
    # push returns the version that WILL contain this update (not yet
    # applied: only 1 of 2 workers pushed) — pulling at it must block
    assert v1 == 1
    got = []
    t = threading.Thread(
        target=lambda: got.append(c1.pull_dense("w", min_version=v1)))
    t.start()
    assert not got
    c2.push_dense("w", np.full(2, 3.0, np.float32))  # completes the step
    t.join(timeout=30)
    value, version = got[0]
    # sync applies the WORKER-MEAN grad: (1 + 3)/2 = 2 -> w = -0.5*2
    np.testing.assert_allclose(value, -1.0)
    assert version == 1
    c1.stop_servers()
    c1.close()
    c2.close()


def test_sparse_embedding_trains(cluster):
    """End-to-end: embedding regression through the PS converges."""
    _, client = cluster
    paddle.seed(0)
    emb = SparseEmbedding(client, "emb_t", (100, 8), rule="adam", lr=0.05)
    head = paddle.nn.Linear(8, 1)
    opt = PSOptimizer(client, layers=head, rule="adam", lr=0.05)
    opt._embeddings.append(emb)

    rng = np.random.default_rng(0)
    ids = rng.integers(0, 20, (16,))
    target = (ids % 3).astype("float32").reshape(-1, 1)

    losses = []
    for _ in range(60):
        out = head(emb(paddle.to_tensor(ids)))
        loss = ((out - paddle.to_tensor(target)) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.2, (losses[0], losses[-1])


ROLE_SCRIPT = """
import os
import numpy as np
import paddle_tpu.distributed.fleet as fleet

fleet.init(is_collective=False)
if fleet.is_server():
    fleet.run_server()           # blocks until a worker stops it
else:
    assert fleet.is_worker()
    client = fleet.init_worker()
    client.create_dense_table("w", (2,), rule="sgd", lr=0.1)
    client.init_dense("w", np.zeros(2, np.float32))
    client.push_dense("w", np.ones(2, np.float32))
    value, _ = client.pull_dense("w")
    assert np.allclose(value, -0.1), value
    fleet.stop_worker()
    print("PS_ROLE_OK")
"""


def _free_port():
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_fleet_ps_role_flow(tmp_path):
    script = tmp_path / "ps_node.py"
    script.write_text(textwrap.dedent(ROLE_SCRIPT))
    port = _free_port()
    base = {**os.environ, "PYTHONPATH": _REPO_ROOT,
            "PADDLE_PSERVERS_IP_PORT_LIST": f"127.0.0.1:{port}",
            "PADDLE_TRAINERS_NUM": "1"}
    server = worker = None
    try:
        server = subprocess.Popen(
            [sys.executable, str(script)],
            env={**base, "TRAINING_ROLE": "PSERVER",
                 "PADDLE_PORT": str(port)},
            cwd=str(tmp_path), stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)
        worker = subprocess.Popen(
            [sys.executable, str(script)],
            env={**base, "TRAINING_ROLE": "TRAINER",
                 "PADDLE_TRAINER_ID": "0"},
            cwd=str(tmp_path), stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)
        wout, _ = worker.communicate(timeout=300)
        sout, _ = server.communicate(timeout=180)
        assert worker.returncode == 0, wout
        assert "PS_ROLE_OK" in wout
        assert server.returncode == 0, sout
    finally:
        for p in (server, worker):
            if p is not None and p.poll() is None:
                p.kill()


def test_ssd_table_exceeds_memory_budget(cluster):
    """Disk-spilling sparse table (VERDICT r2 missing #4): touch far more
    rows than the memory budget; every row survives eviction round trips
    with exact values."""
    servers, client = cluster
    dim, budget = 8, 16
    client.create_sparse_table("big", dim, rule="sgd", lr=1.0,
                               table_class="ssd", max_mem_rows=budget)
    ids = np.arange(200)
    first = client.pull_sparse("big", ids)            # materializes rows
    # push a known grad to every row: value' = value - 1.0 * g
    g = np.tile(np.arange(dim, dtype=np.float32), (len(ids), 1))
    client.push_sparse("big", ids, g)
    # revisit in a different order (forces disk loads of evicted rows)
    order = np.random.default_rng(0).permutation(ids)
    got = client.pull_sparse("big", order)
    want = first[order] - g[order]
    np.testing.assert_allclose(got, want, rtol=1e-6)
    # the hot set respected the budget and the tail lives on disk
    for s in range(2):
        mem, disk = client._call(s, "sparse_stats", "big")
        assert mem <= budget
        assert disk > 0


def test_ssd_table_adam_state_survives_eviction():
    """Optimizer state (m/v/t) must round-trip through the log store, not
    reset on eviction — two adam steps on an evicted row match two adam
    steps on an in-memory reference table."""
    from paddle_tpu.distributed.ps.service import _SparseTable
    from paddle_tpu.distributed.ps.ssd_table import SsdSparseTable

    acc = dict(rule="adam", lr=0.1)
    ssd = SsdSparseTable(4, acc, seed=0, max_mem_rows=2)
    ref = _SparseTable(4, acc, seed=0)
    ids = [0, 1, 2, 3, 4, 5]          # > budget: forces churn
    g = np.ones((len(ids), 4), np.float32)
    ssd.pull(ids)
    ref.pull(ids)
    for _ in range(2):
        ssd.push(ids, g)
        ref.push(ids, g)
    np.testing.assert_allclose(ssd.pull(ids), ref.pull(ids), rtol=1e-6)
    assert ssd.disk_rows > 0


def test_geo_async_mirrors_converge(cluster):
    """Geo-async (VERDICT r2 missing #4): two workers train local mirrors
    toward different targets with periodic delta sync; after syncs both
    mirrors hold the same global rows and the shared row moved toward the
    average of both targets."""
    from paddle_tpu.distributed.ps import GeoSparseMirror

    servers, client = cluster
    w1 = GeoSparseMirror(client, "emb", dim=4, geo_steps=5, lr=0.2)
    w2 = GeoSparseMirror(client, "emb", dim=4, geo_steps=5, lr=0.2)
    target = np.ones(4, np.float32)

    for _ in range(40):
        for w in (w1, w2):
            row = w.lookup([7])[0]
            w.update([7], [(row - target)])   # d/drow ||row - t||^2 / 2

    w1.sync(full_refresh=True)
    w2.sync(full_refresh=True)
    r1 = w1.lookup([7])[0]
    r2 = w2.lookup([7])[0]
    np.testing.assert_allclose(r1, r2, rtol=1e-5)   # same global row
    # converged near the target (both workers pull it the same way)
    assert np.abs(r1 - target).max() < 0.2


def test_geo_local_steps_do_not_touch_server(cluster):
    """Between geo syncs the server must see NO traffic for updates."""
    from paddle_tpu.distributed.ps import GeoSparseMirror

    servers, client = cluster
    w = GeoSparseMirror(client, "emb2", dim=4, geo_steps=1000, lr=0.1)
    w.lookup([3])
    before = client.pull_sparse("emb2", [3]).copy()
    for _ in range(10):
        row = w.lookup([3])[0]
        w.update([3], [row * 0 + 1.0])
    after = client.pull_sparse("emb2", [3])
    np.testing.assert_allclose(before, after)       # untouched globally
    w.sync()
    moved = client.pull_sparse("emb2", [3])
    assert np.abs(moved - before).max() > 0.5       # deltas arrived


def test_multi_slot_datafeed(tmp_path):
    """Reference MultiSlotDataFeed line format: per slot '<n> v1..vn';
    use_var slot declarations auto-install the parser."""
    from paddle_tpu.distributed import InMemoryDataset

    f = tmp_path / "part-000"
    # slots: click (1 int label), ids (sparse int64), dense (3 floats)
    f.write_text("1 1 3 101 102 103 3 0.5 0.25 0.125\n"
                 "1 0 2 7 9 3 1.0 2.0 3.0\n")
    ds = InMemoryDataset()
    ds.init(batch_size=2, use_var=[("click", "int64"), ("ids", "int64"),
                                   ("dense", "float32")])
    ds.set_filelist([str(f)])
    ds.load_into_memory()
    (batch,) = list(ds)
    assert len(batch) == 2
    s0, s1 = batch
    assert s0["click"].tolist() == [1] and s1["click"].tolist() == [0]
    assert s0["ids"].tolist() == [101, 102, 103]
    assert s1["ids"].tolist() == [7, 9]
    np.testing.assert_allclose(s0["dense"], [0.5, 0.25, 0.125])
    # malformed line raises with slot context
    bad = tmp_path / "bad"
    bad.write_text("1 1 5 101\n")
    ds2 = InMemoryDataset()
    ds2.init(batch_size=1, use_var=["click", "ids"])
    ds2.set_filelist([str(bad)])
    with pytest.raises(ValueError, match="ids"):
        ds2.load_into_memory()
        list(ds2)

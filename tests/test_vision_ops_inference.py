"""vision.ops / inference / utils namespace tests (reference patterns:
``test_nms_op.py``, ``test_roi_align_op.py``, ``test_inference_api.py``)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.vision import ops as vops

R = np.random.default_rng(17)


def _rand_boxes(n, size=64):
    xy = R.uniform(0, size - 8, (n, 2)).astype("float32")
    wh = R.uniform(4, 16, (n, 2)).astype("float32")
    return np.concatenate([xy, xy + wh], -1)


def _iou_matrix(a, b):
    x1 = np.maximum(a[:, None, 0], b[None, :, 0])
    y1 = np.maximum(a[:, None, 1], b[None, :, 1])
    x2 = np.minimum(a[:, None, 2], b[None, :, 2])
    y2 = np.minimum(a[:, None, 3], b[None, :, 3])
    inter = np.clip(x2 - x1, 0, None) * np.clip(y2 - y1, 0, None)
    area = lambda v: (v[:, 2] - v[:, 0]) * (v[:, 3] - v[:, 1])
    return inter / (area(a)[:, None] + area(b)[None, :] - inter + 1e-10)


def _nms_ref(boxes, scores, thr):
    order = list(np.argsort(-scores))
    keep = []
    while order:
        i = order.pop(0)
        keep.append(i)
        ious = _iou_matrix(boxes[i:i + 1], boxes[order])[0]
        order = [j for j, v in zip(order, ious) if v <= thr]
    return np.asarray(keep, np.int64)


def test_nms_matches_bruteforce():
    boxes = _rand_boxes(40)
    scores = R.uniform(size=(40,)).astype("float32")
    keep = np.asarray(vops.nms(paddle.to_tensor(boxes), 0.5,
                               scores=paddle.to_tensor(scores))._read())
    np.testing.assert_array_equal(keep, _nms_ref(boxes, scores, 0.5))
    # kept boxes are mutually below the IoU threshold
    kb = boxes[keep]
    m = _iou_matrix(kb, kb)
    np.fill_diagonal(m, 0)
    assert m.max() <= 0.5 + 1e-6


def test_nms_topk_and_categories():
    boxes = _rand_boxes(30)
    scores = R.uniform(size=(30,)).astype("float32")
    cats = R.integers(0, 3, 30)
    keep = np.asarray(vops.nms(paddle.to_tensor(boxes), 0.5,
                               scores=paddle.to_tensor(scores),
                               category_idxs=paddle.to_tensor(cats),
                               categories=[0, 1, 2], top_k=5)._read())
    assert len(keep) <= 5
    # per-class greedy reference, merged by score
    ref = []
    for c in (0, 1, 2):
        idx = np.where(cats == c)[0]
        ref.extend(idx[_nms_ref(boxes[idx], scores[idx], 0.5)])
    ref = sorted(ref, key=lambda i: -scores[i])[:len(keep)]
    np.testing.assert_array_equal(keep, ref)


def _roi_align_ref(x, boxes, img_idx, out, scale, s):
    n, c, h, w = x.shape
    res = np.zeros((len(boxes), c, out, out), "float32")

    def bilinear(img, y, xq):
        y0, x0 = int(np.floor(y)), int(np.floor(xq))
        y0c, x0c = np.clip(y0, 0, h - 1), np.clip(x0, 0, w - 1)
        y1c, x1c = np.clip(y0 + 1, 0, h - 1), np.clip(x0 + 1, 0, w - 1)
        wy, wx = np.clip(y - y0, 0, 1), np.clip(xq - x0, 0, 1)
        return (img[:, y0c, x0c] * (1 - wy) * (1 - wx)
                + img[:, y1c, x0c] * wy * (1 - wx)
                + img[:, y0c, x1c] * (1 - wy) * wx
                + img[:, y1c, x1c] * wy * wx)

    for r, b in enumerate(boxes):
        img = x[img_idx[r]]
        x1, y1, x2, y2 = b * scale - 0.5
        bw, bh = max(x2 - x1, 1e-3), max(y2 - y1, 1e-3)
        for oy in range(out):
            for ox in range(out):
                acc = 0.0
                for sy in range(s):
                    for sx in range(s):
                        yy = y1 + (oy + (sy + 0.5) / s) * bh / out
                        xx = x1 + (ox + (sx + 0.5) / s) * bw / out
                        acc += bilinear(img, yy, xx)
                res[r, :, oy, ox] = acc / (s * s)
    return res


def test_roi_align_matches_bruteforce():
    x = R.normal(size=(2, 3, 16, 16)).astype("float32")
    boxes = _rand_boxes(5, 14).astype("float32")
    boxes_num = np.array([3, 2], "int32")
    out = vops.roi_align(paddle.to_tensor(x), paddle.to_tensor(boxes),
                         paddle.to_tensor(boxes_num), output_size=4,
                         spatial_scale=1.0, sampling_ratio=2,
                         aligned=True)
    img_idx = np.repeat(np.arange(2), boxes_num)
    ref = _roi_align_ref(x, boxes, img_idx, 4, 1.0, 2)
    np.testing.assert_allclose(np.asarray(out._read()), ref, atol=1e-4)


def test_roi_pool_shape():
    x = R.normal(size=(1, 2, 16, 16)).astype("float32")
    boxes = _rand_boxes(3, 14)
    out = vops.roi_pool(paddle.to_tensor(x), paddle.to_tensor(boxes),
                        paddle.to_tensor(np.array([3], "int32")), 4)
    assert tuple(out.shape) == (3, 2, 4, 4)
    assert np.isfinite(np.asarray(out._read())).all()


def test_box_coder_roundtrip():
    priors = _rand_boxes(6)
    targets = _rand_boxes(4)
    var = np.ones((6, 4), "float32")
    enc = vops.box_coder(paddle.to_tensor(priors), paddle.to_tensor(var),
                         paddle.to_tensor(targets),
                         code_type="encode_center_size")
    dec = vops.box_coder(paddle.to_tensor(priors), paddle.to_tensor(var),
                         enc, code_type="decode_center_size")
    got = np.asarray(dec._read())  # [T, P, 4]
    for t in range(4):
        for p in range(6):
            np.testing.assert_allclose(got[t, p], targets[t], atol=1e-3)


def test_inference_predictor_roundtrip(tmp_path):
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(6, 12), nn.ReLU(), nn.Linear(12, 3))
    net.eval()
    prefix = str(tmp_path / "model")
    paddle.jit.save(net, prefix,
                    input_spec=[paddle.static.InputSpec([None, 6],
                                                        "float32")])
    cfg = paddle.inference.Config(prefix)
    cfg.enable_memory_optim()
    pred = paddle.inference.create_predictor(cfg)
    names = pred.get_input_names()
    x = R.normal(size=(4, 6)).astype("float32")
    pred.get_input_handle(names[0]).copy_from_cpu(x)
    outs = pred.run()
    ref = np.asarray(net(paddle.to_tensor(x))._read())
    np.testing.assert_allclose(outs[0], ref, atol=1e-5)
    h = pred.get_output_handle(pred.get_output_names()[0])
    np.testing.assert_allclose(h.copy_to_cpu(), ref, atol=1e-5)


def test_utils_and_misc():
    import warnings

    from paddle_tpu.utils import deprecated, unique_name
    from paddle_tpu.utils.dlpack import from_dlpack, to_dlpack

    @deprecated(update_to="paddle.new_api", since="2.0")
    def old():
        return 42

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        assert old() == 42
        assert any("deprecated" in str(x.message) for x in w)

    n1, n2 = unique_name.generate("fc"), unique_name.generate("fc")
    assert n1 != n2

    t = paddle.to_tensor(np.arange(6, dtype="float32"))
    back = from_dlpack(to_dlpack(t))
    np.testing.assert_allclose(np.asarray(back._read()),
                               np.arange(6, dtype="float32"))

    assert paddle.iinfo("int32").max == 2**31 - 1
    assert paddle.finfo("float32").eps > 0
    assert paddle.finfo("bfloat16").bits == 16

    r = paddle.batch(lambda: iter(range(5)), batch_size=2)
    assert list(r()) == [[0, 1], [2, 3], [4]]
    assert paddle.version.full_version


def test_inference_predictor_named_io_and_fresh_process(tmp_path):
    """Hardened Predictor (VERDICT r2 weak #7): input names come from the
    export's InputSpec, Config.summary documents no-op switches, batched
    run splits/concats, and a FRESH process can serve the saved model."""
    import json
    import subprocess
    import sys

    paddle.seed(1)
    net = nn.Sequential(nn.Linear(5, 8), nn.ReLU(), nn.Linear(8, 2))
    net.eval()
    prefix = str(tmp_path / "served")
    paddle.jit.save(net, prefix, input_spec=[
        paddle.static.InputSpec([None, 5], "float32", name="features")])

    cfg = paddle.inference.Config(prefix)
    cfg.enable_use_gpu(100, 0)
    cfg.switch_ir_optim(True)
    s = cfg.summary()
    assert "NO-OP" in s and "gpu" in s
    pred = paddle.inference.create_predictor(cfg)
    assert pred.get_input_names() == ["features"]

    x = R.normal(size=(7, 5)).astype("float32")
    ref = np.asarray(net(paddle.to_tensor(x))._read())
    # batched run: chunks of 3 (7 -> 3+3+1) must equal the one-shot run
    outs = pred.run_batch([x], batch_size=3)
    np.testing.assert_allclose(outs[0], ref, atol=1e-5)

    # fresh-process serving: no model code, only the saved artifacts
    script = f"""
import json
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
import paddle_tpu as paddle
cfg = paddle.inference.Config({prefix!r})
pred = paddle.inference.create_predictor(cfg)
assert pred.get_input_names() == ["features"], pred.get_input_names()
x = np.load({str(tmp_path / "x.npy")!r})
h = pred.get_input_handle("features")
h.copy_from_cpu(x)
out = pred.run()[0]
np.save({str(tmp_path / "out.npy")!r}, out)
print("SERVED", out.shape)
"""
    np.save(tmp_path / "x.npy", x)
    r = subprocess.run([sys.executable, "-c", script],
                       capture_output=True, text=True, cwd="/root/repo",
                       env={**__import__("os").environ,
                            "PYTHONPATH": "/root/repo",
                            "JAX_PLATFORMS": "cpu"}, timeout=300)
    assert r.returncode == 0, (r.stdout, r.stderr)
    assert "SERVED" in r.stdout
    np.testing.assert_allclose(np.load(tmp_path / "out.npy"), ref,
                               atol=1e-5)

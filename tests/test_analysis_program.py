"""Whole-program jaxpr analyzer (ISSUE 16): the dataflow framework
(sub-jaxpr walk, def-use/live ranges, static peak-HBM sweep), collective
schedule extraction + the store-backed runtime verifier, eqn-level
provenance of the PDT22x/23x passes, the jit-capture wiring (audit-once,
``hbm.static_peak_bytes`` gauge, PDT242 shape-fork sharing the
``compile.retrace`` vocabulary), and the per-code audit-counts plumbing
the bench round record snapshots."""
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax

import paddle_tpu as paddle
from paddle_tpu import analysis
from paddle_tpu import observability as obs
from paddle_tpu.analysis import LintWarning, Severity
from paddle_tpu.analysis import program as prog
from paddle_tpu.core import errors


@pytest.fixture(autouse=True)
def _fresh():
    analysis.reset_reported()
    yield
    analysis.reset_reported()


@pytest.fixture
def _mode():
    old = paddle.get_flags("analysis")["analysis"]

    def set_mode(m):
        paddle.set_flags({"analysis": m})

    yield set_mode
    paddle.set_flags({"analysis": old})


@pytest.fixture
def metrics_on():
    old = paddle.get_flags("metrics")["metrics"]
    paddle.set_flags({"metrics": True})
    yield
    paddle.set_flags({"metrics": old})


# ==========================================================================
# dataflow framework
# ==========================================================================

def test_all_eqns_walks_cond_scan_while_pjit():
    inner = jax.jit(lambda v: v * 3.0)

    def f(p, xs, x):
        y = lax.cond(p, lambda v: v * 2.0, lambda v: v + 1.0, x)
        c, out = lax.scan(lambda c, s: (c + s, c), y, xs)
        (c,) = lax.while_loop(lambda v: v[0].sum() < 10.0,
                              lambda v: (v[0] + 1.0,), (c,))
        return inner(c) + out.sum()

    closed = jax.make_jaxpr(f)(True, jnp.ones((3, 4), jnp.float32),
                               jnp.ones((4,), jnp.float32))
    paths = {p for _, p in prog.all_eqns(closed)}
    assert any(p.startswith("branches[0]") for p in paths), paths
    assert any(p.startswith("branches[1]") for p in paths), paths
    assert any("body_jaxpr" in p for p in paths), paths   # while body
    assert any("cond_jaxpr" in p for p in paths), paths   # while cond
    assert any(p.startswith("jaxpr") for p in paths), paths  # scan/pjit
    # top-level eqns carry the empty path
    assert "" in paths


def test_def_use_and_live_ranges():
    def f(x):
        a = x * 2.0
        b = a + 1.0
        return b

    j = jax.make_jaxpr(f)(jnp.ones((8,), jnp.float32)).jaxpr
    x = j.invars[0]
    uses = prog.def_use(j)
    assert uses[x] == [0]                      # consumed by eqn 0 only
    ranges = prog.live_ranges(j)
    assert ranges[x] == (-1, 0)                # input, dies after eqn 0
    out = j.outvars[0]
    assert ranges[out][1] == len(j.eqns)       # outvar survives program


def test_static_peak_bytes_counts_live_set_and_donation_alias():
    kib = 1024 * 4  # 1024 f32

    def step(w, g):
        return w - 0.1 * g

    closed = jax.make_jaxpr(step)(jnp.ones((1024,), jnp.float32),
                                  jnp.ones((1024,), jnp.float32))
    base = prog.static_peak_bytes(closed)
    assert base >= 3 * kib                     # w, g, out live together
    # donating w (shape/dtype matches the output) aliases it onto the
    # result: the estimate drops by exactly one buffer
    donated = prog.static_peak_bytes(closed, donated=(0,))
    assert donated == base - kib


def test_static_peak_bytes_attributes_inner_scan_peak():
    def f(xs):
        def body(c, s):
            big = jnp.outer(s, s)              # transient inside body
            return c + big.sum(), big.sum()
        return lax.scan(body, 0.0, xs)

    closed = jax.make_jaxpr(f)(jnp.ones((4, 256), jnp.float32))
    peak = prog.static_peak_bytes(closed)
    # the 256x256 transient inside the scan body dominates the
    # top-level live set and must show up in the estimate
    assert peak >= 256 * 256 * 4


# ==========================================================================
# collective schedule + hash
# ==========================================================================

def test_collective_schedule_extraction_and_hash():
    def f(x):
        a = lax.psum(x, "i")
        return lax.pmax(a, "i")

    closed = jax.make_jaxpr(f, axis_env=[("i", 2)])(
        jnp.ones((4,), jnp.float32))
    sched = prog.collective_schedule(closed)
    assert [op.prim for op in sched] == ["psum", "pmax"]
    assert sched[0].axes == ("i",)
    assert sched[0].shape == (4,) and sched[0].dtype == "float32"
    h = prog.schedule_hash(sched)
    assert h == prog.schedule_hash(sched)                  # stable
    assert prog.schedule_hash(list(reversed(sched))) != h  # ordered
    assert prog.schedule_hash([]) != h


def test_collective_schedule_reaches_into_subjaxprs():
    def f(p, x):
        return lax.cond(p, lambda v: lax.psum(v, "i") * 2.0,
                        lambda v: lax.psum(v, "i") + 1.0, x)

    closed = jax.make_jaxpr(f, axis_env=[("i", 2)])(
        True, jnp.ones((4,), jnp.float32))
    sched = prog.collective_schedule(closed)
    assert len(sched) == 2                     # one psum per branch
    assert all(op.path.startswith("branches[") for op in sched)


class _FakeStore:
    """bstore.Store test double: shared dict, StoreTimeoutError on a
    missing key (the real store's timeout contract)."""

    def __init__(self, kv):
        self.kv = kv

    def set(self, k, v):
        self.kv[k] = v

    def get(self, k, timeout=None):
        if k not in self.kv:
            raise errors.StoreTimeoutError(f"no key {k}")
        return self.kv[k]


def test_verify_schedule_agreement_divergence_and_missing_peer():
    kv = {}
    a, b = _FakeStore(kv), _FakeStore(kv)
    # first rank up: the peer has not published yet -> skipped, agrees
    assert prog.verify_schedule(a, "g", "n0", ["n0", "n1"], "aaaa",
                                timeout=0.0)
    # second rank agrees with the published hash
    assert prog.verify_schedule(b, "g", "n1", ["n0", "n1"], "aaaa",
                                timeout=0.0)
    # a divergent rank reports PDT223 and raises the coded error
    with analysis.collect() as diags:
        with pytest.raises(errors.CollectiveScheduleError,
                           match="divergence"):
            prog.verify_schedule(b, "g", "n1", ["n0", "n1"], "bbbb",
                                 timeout=0.0)
    assert any(d.code == "PDT223" for d in diags), \
        [d.format() for d in diags]
    # raise_on_divergence=False: reports, returns False, does not raise
    with analysis.collect() as diags2:
        ok = prog.verify_schedule(b, "g", "n1", ["n0", "n1"], "bbbb",
                                  timeout=0.0, raise_on_divergence=False)
    assert ok is False
    assert any(d.code == "PDT223" for d in diags2)


def test_collective_schedule_error_is_coded():
    assert issubclass(errors.CollectiveScheduleError, errors.EnforceNotMet)
    assert errors.CollectiveScheduleError("x").error_code == "PDT-E023"


# ==========================================================================
# pass provenance (the goldens in test_analysis.py cover trigger /
# near-miss / suppression for every code; here: eqn-level anchoring)
# ==========================================================================

def test_pdt221_divergent_cond_anchors_to_the_cond_eqn():
    def f(p, x):
        return lax.cond(p, lambda v: lax.psum(v, "i"),
                        lambda v: v * 2.0, x)

    closed = jax.make_jaxpr(f, axis_env=[("i", 2)])(
        True, jnp.ones((4,), jnp.float32))
    hits = [d for d in analysis.check_jaxpr(closed)
            if d.code == "PDT221"]
    assert hits and hits[0].severity == Severity.ERROR
    # provenance: the finding carries the cond eqn's user source site —
    # this very file, at a positive line number
    assert hits[0].file.endswith("test_analysis_program.py"), hits[0]
    assert hits[0].line > 0
    assert "branch" in hits[0].message


def test_pdt231_read_after_donation_anchors_to_consuming_eqn():
    def f(w, g):
        return (w - g).sum()                   # no (1024,) output left

    closed = jax.make_jaxpr(f)(jnp.ones((1024,), jnp.float32),
                               jnp.ones((1024,), jnp.float32))
    hits = [d for d in analysis.check_jaxpr(closed, donated=(0,))
            if d.code == "PDT231"]
    assert hits and hits[0].severity == Severity.ERROR
    # provenance: anchored to the eqn that consumed the donated buffer
    assert hits[0].file.endswith("test_analysis_program.py"), hits[0]
    assert hits[0].line > 0
    # near-miss: a matching output supersedes the donated input
    clean = jax.make_jaxpr(lambda w, g: w - g)(
        jnp.ones((1024,), jnp.float32), jnp.ones((1024,), jnp.float32))
    assert not [d for d in analysis.check_jaxpr(clean, donated=(0,))
                if d.code == "PDT231"]


# ==========================================================================
# jit capture wiring: audit-once, gauge, shape-fork retrace vocabulary
# ==========================================================================

def test_capture_audit_stashes_peak_and_schedule_hash():
    w = paddle.to_tensor(np.ones((256,), np.float32))

    @paddle.jit.to_static
    def audited_step(x):
        return (x * 2.0 + w.sum()).mean()

    x = paddle.to_tensor(np.ones((256,), np.float32))
    with analysis.collect():
        audited_step(x)
    exe = audited_step.concrete_program(x)
    assert exe.jaxpr is None                   # still released after audit
    assert exe.static_peak_bytes > 0
    assert exe.schedule_hash == prog.schedule_hash([])  # no collectives

    from paddle_tpu import jit as jit_mod
    assert jit_mod._static_peak_bytes("audited_step") \
        == exe.static_peak_bytes
    assert jit_mod._program_state_bytes("audited_step") > 0


def test_hbm_static_peak_gauge_reads_live_executables(metrics_on):
    from paddle_tpu.observability import metrics as obs_metrics

    w = paddle.to_tensor(np.ones((128,), np.float32))

    @paddle.jit.to_static
    def gauged_step(x):
        return (x + w).sum()

    x = paddle.to_tensor(np.ones((128,), np.float32))
    with analysis.collect():
        gauged_step(x)
    exe = gauged_step.concrete_program(x)
    snap = obs_metrics.registry().snapshot()["hbm"]
    assert snap["static_peak_bytes"]["fn=gauged_step"] \
        == exe.static_peak_bytes
    # sits next to the measured residency gauge, same labels
    assert snap["program_state_bytes"]["fn=gauged_step"] > 0


def test_shape_fork_pdt242_fires_and_shares_retrace_vocabulary(
        metrics_on):
    obs.events.clear()

    @paddle.jit.to_static
    def forked(x):
        return x * 2.0

    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        with analysis.collect() as diags:
            for n in (4, 5, 6):                # 3 shape-only variants
                forked(paddle.to_tensor(np.ones((n,), np.float32)))
    hits = [d for d in diags if d.code == "PDT242"]
    assert hits, [d.format() for d in diags]
    assert "shape-as-data" in hits[0].message
    # runtime evidence rides the SAME vocabulary: a compile.retrace
    # event with the shape-as-data cause and the variant count
    retr = [e for e in obs.tail() if e["kind"] == "compile.retrace"
            and e.get("cause", "").startswith("shape-as-data")]
    assert retr and retr[-1]["count"] == 3
    assert retr[-1]["fn"] == "forked"


def test_shape_fork_below_limit_is_silent():
    @paddle.jit.to_static
    def two_shapes(x):
        return x + 1.0

    with analysis.collect() as diags:
        for n in (4, 5):                       # below SHAPE_FORK_LIMIT
            two_shapes(paddle.to_tensor(np.ones((n,), np.float32)))
    assert not [d for d in diags if d.code == "PDT242"]


def test_strip_shapes_collapses_shape_only_variants():
    a = (("T", (4, 8), "float32"), 3, "k")
    b = (("T", (9, 8), "float32"), 3, "k")
    c = (("T", (4, 8), "int32"), 3, "k")
    assert prog.strip_shapes(a) == prog.strip_shapes(b)
    assert prog.strip_shapes(a) != prog.strip_shapes(c)


# ==========================================================================
# audit entry points: counts, mode gating, zero per-dispatch work
# ==========================================================================

def test_audit_counts_accumulate_and_reset():
    analysis.audit_counts(reset=True)
    closed = jax.make_jaxpr(lambda x: x * 2.0)(3.0)  # weak input: PDT205
    with analysis.collect():
        analysis.audit_jaxpr(closed, where="t")
        analysis.audit_jaxpr(closed, where="t")
    assert analysis.audit_counts().get("PDT205", 0) >= 2
    analysis.audit_counts(reset=True)
    assert analysis.audit_counts() == {}


def test_audit_runs_at_capture_not_per_dispatch():
    @paddle.jit.to_static
    def dispatched(x):
        return x + 1.0

    x = paddle.to_tensor(np.ones((4,), np.float32))
    with analysis.collect():
        dispatched(x)                          # capture: audit runs here
    analysis.audit_counts(reset=True)
    dispatched(x)
    dispatched(x)                              # cache hits: zero audit work
    assert analysis.audit_counts() == {}


def test_audit_jitted_and_executable_gated_off(_mode):
    _mode("off")
    assert analysis.audit_jitted(lambda x: x * 2.0,
                                 (jnp.ones((3,), jnp.float32),),
                                 where="t") is None

    class _Exe:
        jaxpr = jax.make_jaxpr(lambda x: x + 1.0)(
            jnp.ones((3,), jnp.float32))

    assert analysis.audit_executable(_Exe(), where="t") is None


def test_audit_jitted_swallows_trace_failures():
    def broken(x):
        raise RuntimeError("tracing explodes")

    assert analysis.audit_jitted(broken, (jnp.ones((3,),),),
                                 where="t") is None

"""Regression tests for the round-1 advisor findings (ADVICE.md)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.core.tensor import Tensor


def test_minimize_applies_gradient_once():
    # canonical idiom: loss.backward(); opt.minimize(loss) must not
    # double-accumulate (ADVICE high: minimize used to re-run backward)
    lin = nn.Linear(4, 1)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=lin.parameters())
    x = paddle.to_tensor(np.ones((2, 4), np.float32))
    w0 = lin.weight.numpy().copy()
    loss = lin(x).sum()
    loss.backward()
    g = lin.weight.grad.numpy().copy()
    opt.minimize(loss)
    np.testing.assert_allclose(lin.weight.numpy(), w0 - 0.1 * g, rtol=1e-6)


def test_scaler_minimize_applies_gradient_once():
    lin = nn.Linear(4, 1)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=lin.parameters())
    scaler = paddle.amp.GradScaler(init_loss_scaling=2.0,
                                   use_dynamic_loss_scaling=False)
    x = paddle.to_tensor(np.ones((2, 4), np.float32))
    w0 = lin.weight.numpy().copy()
    loss = lin(x).sum()
    scaled = scaler.scale(loss)
    scaled.backward()
    scaler.minimize(opt, scaled)
    # grad of sum(x @ w + b) wrt w is column-sums of x = 2.0 each
    np.testing.assert_allclose(lin.weight.numpy(), w0 - 0.1 * 2.0,
                               rtol=1e-5)


def test_pool_positional_signature_matches_reference():
    x = paddle.to_tensor(np.random.rand(1, 1, 6, 6).astype(np.float32))
    # reference MaxPool2D order: kernel, stride, padding, RETURN_MASK, ...
    out = nn.MaxPool2D(2, 2, 0, True)(x)
    assert isinstance(out, (tuple, list)) and len(out) == 2  # (out, mask)
    # reference AvgPool1D order: kernel, stride, padding, EXCLUSIVE
    x1 = paddle.to_tensor(np.random.rand(1, 1, 6).astype(np.float32))
    out1 = nn.AvgPool1D(2, 2, 0, True)(x1)
    assert out1.shape == [1, 1, 3]
    # ceil_mode still reachable by keyword
    out2 = nn.MaxPool2D(2, 2, 0, ceil_mode=True)(
        paddle.to_tensor(np.random.rand(1, 1, 5, 5).astype(np.float32)))
    assert out2.shape == [1, 1, 3, 3]


@pytest.mark.parametrize("mode,npmode", [("reflect", "reflect"),
                                         ("replicate", "edge"),
                                         ("circular", "wrap")])
def test_conv2d_padding_mode(mode, npmode):
    rng = np.random.RandomState(0)
    x = rng.rand(1, 2, 5, 5).astype(np.float32)
    conv = nn.Conv2D(2, 3, 3, padding=1, padding_mode=mode)
    out = conv(paddle.to_tensor(x))
    # reference semantics: pad input with the mode, then valid conv
    xp = np.pad(x, [(0, 0), (0, 0), (1, 1), (1, 1)], mode=npmode)
    conv_ref = nn.Conv2D(2, 3, 3, padding=0)
    conv_ref.weight._write(conv.weight._read())
    conv_ref.bias._write(conv.bias._read())
    ref = conv_ref(paddle.to_tensor(xp))
    np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=1e-5,
                               atol=1e-5)


def test_jit_mutated_explicit_arg_written_back():
    # ADVICE medium: a to_static fn that mutates an explicit-arg tensor must
    # write the mutation back to the caller's tensor (per call), and grads
    # must not be mis-offset.
    @paddle.jit.to_static
    def step(buf, x):
        y = (x * 2.0).sum()
        buf._adopt(buf + 1.0)
        return y

    buf = paddle.to_tensor(np.zeros((3,), np.float32))
    x = paddle.to_tensor(np.ones((3,), np.float32))
    r0 = step(buf, x)          # step 0: discovery (eager)
    np.testing.assert_allclose(buf.numpy(), np.ones(3), rtol=1e-6)
    r1 = step(buf, x)          # compiled path
    np.testing.assert_allclose(buf.numpy(), 2 * np.ones(3), rtol=1e-6)
    buf2 = paddle.to_tensor(np.full((3,), 10.0, np.float32))
    step(buf2, x)              # mutation lands on THIS call's tensor
    np.testing.assert_allclose(buf2.numpy(), np.full(3, 11.0), rtol=1e-6)
    np.testing.assert_allclose(buf.numpy(), 2 * np.ones(3), rtol=1e-6)
    assert float(r0) == float(r1) == 6.0


def test_jit_arg_mutation_with_grads():
    lin = nn.Linear(3, 1)
    opt = paddle.optimizer.SGD(learning_rate=0.0,
                               parameters=lin.parameters())

    @paddle.jit.to_static
    def step(counter, x):
        loss = lin(x).sum()
        loss.backward()
        opt.step()
        opt.clear_grad()
        counter._adopt(counter + 1.0)
        return loss

    counter = paddle.to_tensor(np.zeros((), np.float32))
    x = paddle.to_tensor(np.ones((2, 3), np.float32))
    step(counter, x)
    step(counter, x)
    step(counter, x)
    assert float(counter) == 3.0

"""Regression tests for the round-1 advisor findings (ADVICE.md)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.core.tensor import Tensor


def test_minimize_applies_gradient_once():
    # canonical idiom: loss.backward(); opt.minimize(loss) must not
    # double-accumulate (ADVICE high: minimize used to re-run backward)
    lin = nn.Linear(4, 1)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=lin.parameters())
    x = paddle.to_tensor(np.ones((2, 4), np.float32))
    w0 = lin.weight.numpy().copy()
    loss = lin(x).sum()
    loss.backward()
    g = lin.weight.grad.numpy().copy()
    opt.minimize(loss)
    np.testing.assert_allclose(lin.weight.numpy(), w0 - 0.1 * g, rtol=1e-6)


def test_scaler_minimize_applies_gradient_once():
    lin = nn.Linear(4, 1)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=lin.parameters())
    scaler = paddle.amp.GradScaler(init_loss_scaling=2.0,
                                   use_dynamic_loss_scaling=False)
    x = paddle.to_tensor(np.ones((2, 4), np.float32))
    w0 = lin.weight.numpy().copy()
    loss = lin(x).sum()
    scaled = scaler.scale(loss)
    scaled.backward()
    scaler.minimize(opt, scaled)
    # grad of sum(x @ w + b) wrt w is column-sums of x = 2.0 each
    np.testing.assert_allclose(lin.weight.numpy(), w0 - 0.1 * 2.0,
                               rtol=1e-5)


def test_pool_positional_signature_matches_reference():
    x = paddle.to_tensor(np.random.rand(1, 1, 6, 6).astype(np.float32))
    # reference MaxPool2D order: kernel, stride, padding, RETURN_MASK, ...
    out = nn.MaxPool2D(2, 2, 0, True)(x)
    assert isinstance(out, (tuple, list)) and len(out) == 2  # (out, mask)
    # reference AvgPool1D order: kernel, stride, padding, EXCLUSIVE
    x1 = paddle.to_tensor(np.random.rand(1, 1, 6).astype(np.float32))
    out1 = nn.AvgPool1D(2, 2, 0, True)(x1)
    assert out1.shape == [1, 1, 3]
    # ceil_mode still reachable by keyword
    out2 = nn.MaxPool2D(2, 2, 0, ceil_mode=True)(
        paddle.to_tensor(np.random.rand(1, 1, 5, 5).astype(np.float32)))
    assert out2.shape == [1, 1, 3, 3]


@pytest.mark.parametrize("mode,npmode", [("reflect", "reflect"),
                                         ("replicate", "edge"),
                                         ("circular", "wrap")])
def test_conv2d_padding_mode(mode, npmode):
    rng = np.random.RandomState(0)
    x = rng.rand(1, 2, 5, 5).astype(np.float32)
    conv = nn.Conv2D(2, 3, 3, padding=1, padding_mode=mode)
    out = conv(paddle.to_tensor(x))
    # reference semantics: pad input with the mode, then valid conv
    xp = np.pad(x, [(0, 0), (0, 0), (1, 1), (1, 1)], mode=npmode)
    conv_ref = nn.Conv2D(2, 3, 3, padding=0)
    conv_ref.weight._write(conv.weight._read())
    conv_ref.bias._write(conv.bias._read())
    ref = conv_ref(paddle.to_tensor(xp))
    np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=1e-5,
                               atol=1e-5)


def test_jit_mutated_explicit_arg_written_back():
    # ADVICE medium: a to_static fn that mutates an explicit-arg tensor must
    # write the mutation back to the caller's tensor (per call), and grads
    # must not be mis-offset.
    @paddle.jit.to_static
    def step(buf, x):
        y = (x * 2.0).sum()
        buf._adopt(buf + 1.0)
        return y

    buf = paddle.to_tensor(np.zeros((3,), np.float32))
    x = paddle.to_tensor(np.ones((3,), np.float32))
    r0 = step(buf, x)          # step 0: discovery (eager)
    np.testing.assert_allclose(buf.numpy(), np.ones(3), rtol=1e-6)
    r1 = step(buf, x)          # compiled path
    np.testing.assert_allclose(buf.numpy(), 2 * np.ones(3), rtol=1e-6)
    buf2 = paddle.to_tensor(np.full((3,), 10.0, np.float32))
    step(buf2, x)              # mutation lands on THIS call's tensor
    np.testing.assert_allclose(buf2.numpy(), np.full(3, 11.0), rtol=1e-6)
    np.testing.assert_allclose(buf.numpy(), 2 * np.ones(3), rtol=1e-6)
    assert float(r0) == float(r1) == 6.0


def test_jit_arg_mutation_with_grads():
    lin = nn.Linear(3, 1)
    opt = paddle.optimizer.SGD(learning_rate=0.0,
                               parameters=lin.parameters())

    @paddle.jit.to_static
    def step(counter, x):
        loss = lin(x).sum()
        loss.backward()
        opt.step()
        opt.clear_grad()
        counter._adopt(counter + 1.0)
        return loss

    counter = paddle.to_tensor(np.zeros((), np.float32))
    x = paddle.to_tensor(np.ones((2, 3), np.float32))
    step(counter, x)
    step(counter, x)
    step(counter, x)
    assert float(counter) == 3.0


# ---- round-3 advisor findings ----

def test_transformed_distribution_event_rank_elementwise_over_eventful():
    # ADVICE r3 medium: chaining an elementwise transform over an
    # event-ful base (Dirichlet) must SUM the per-element log-det over
    # the event dim, not broadcast it
    from paddle_tpu.distribution import Dirichlet, ExpTransform
    from paddle_tpu.distribution.transformed_distribution import (
        TransformedDistribution,
    )

    base = Dirichlet(paddle.to_tensor(np.array([2.0, 3.0, 4.0],
                                               np.float32)))
    d = TransformedDistribution(base, [ExpTransform()])
    assert tuple(d.event_shape) == (3,)
    assert tuple(d.batch_shape) == ()
    x = np.array([0.2, 0.3, 0.5], np.float32)
    y = np.exp(x)
    lp = d.log_prob(paddle.to_tensor(y)).numpy()
    # change of variables: log p_Y(y) = log p_X(x) - sum_i log|dy_i/dx_i|
    expected = base.log_prob(paddle.to_tensor(x)).numpy() - x.sum()
    assert lp.shape == ()  # scalar, not (3,)
    np.testing.assert_allclose(lp, expected, rtol=1e-5)


def test_transformed_distribution_event_rank_chain_with_stickbreaking():
    # elementwise Affine chained into event-rank-1 StickBreaking: the
    # affine log-det must reduce over the absorbed event dim
    from paddle_tpu.distribution import (
        AffineTransform, Normal, StickBreakingTransform,
    )
    from paddle_tpu.distribution.transformed_distribution import (
        TransformedDistribution,
    )

    base = Normal(paddle.to_tensor(np.zeros((2, 3), np.float32)),
                  paddle.to_tensor(np.ones((2, 3), np.float32)))
    d = TransformedDistribution(
        base, [AffineTransform(0.0, 2.0), StickBreakingTransform()])
    assert tuple(d.event_shape) == (4,)
    assert tuple(d.batch_shape) == (2,)
    y = d.sample().numpy()
    lp = d.log_prob(paddle.to_tensor(y)).numpy()
    assert lp.shape == (2,)
    # cross-check one row against the manual change-of-variables
    import jax.numpy as jnp
    sb = StickBreakingTransform()
    x_sb = sb._inverse(jnp.asarray(y[0]))             # pre-stickbreak
    x = np.asarray(x_sb) / 2.0                        # pre-affine
    manual = (base.log_prob(
        paddle.to_tensor(np.stack([x, x]))).numpy()[0].sum()
        - np.log(2.0) * 3
        - np.asarray(sb._forward_log_det_jacobian(x_sb)))
    np.testing.assert_allclose(lp[0], manual, rtol=1e-4)


def test_geo_mirror_eviction_spares_touched_rows():
    # ADVICE r3 low: cap eviction must run before the touched-set clear
    from paddle_tpu.distributed.ps.service import GeoSparseMirror

    class _FakeClient:
        def __init__(self):
            self.rows = {}

        def create_sparse_table(self, name, dim, rule="sum", seed=0):
            pass

        def push_sparse(self, name, ids, deltas):
            for i, dv in zip(ids, deltas):
                self.rows[int(i)] = self.rows.get(
                    int(i), np.zeros_like(dv)) + dv

        def pull_sparse(self, name, ids):
            return [self.rows.get(int(i), np.zeros(4, np.float32))
                    for i in ids]

    m = GeoSparseMirror(_FakeClient(), "t", dim=4, geo_steps=1000,
                        max_mirror_rows=4)
    for i in range(4):
        m.lookup([i])
    # touch rows 2,3 (they become hot) then add overflow rows 4,5
    m.update([2, 3], np.ones((2, 4), np.float32))
    m.lookup([4])
    m.lookup([5])
    m.sync()
    # hot rows 2,3 must survive; eviction takes cold rows first
    assert 2 in m._local and 3 in m._local
    assert len(m._local) <= 4


def test_spectral_norm_nonuniform_start_vector():
    # ADVICE r3 low: all-ones u is orthogonal to zero-sum singular
    # vectors; a centered rank-1 weight must still normalize correctly
    import paddle_tpu.nn.functional as F

    v1 = np.array([1.0, -1.0, 0.0], np.float32) / np.sqrt(2)
    w = 10.0 * np.outer(v1, np.array([1.0, 2.0, 3.0], np.float32))
    out = F.spectral_norm(paddle.to_tensor(w), dim=0,
                          power_iters=20).numpy()
    sigma = np.linalg.svd(w, compute_uv=False)[0]
    np.testing.assert_allclose(
        np.linalg.svd(out, compute_uv=False)[0], 1.0, rtol=1e-3)
    np.testing.assert_allclose(out, w / sigma, rtol=1e-3, atol=1e-5)


def test_ssd_table_batch_push_matches_scalar_path():
    # batch (unique-id) push must produce the same rows as the
    # row-at-a-time path, including adam state evolution
    from paddle_tpu.distributed.ps.ssd_table import SsdSparseTable

    acc = {"rule": "adam", "lr": 0.1}
    a = SsdSparseTable(4, acc, seed=0, max_mem_rows=8)
    b = SsdSparseTable(4, acc, seed=0, max_mem_rows=8)
    rng = np.random.default_rng(1)
    for _ in range(3):
        g = rng.normal(size=(4, 4)).astype(np.float32)
        a.push([0, 1, 2, 3], g)                # batch path
        for i in range(4):
            b.push([i], g[i:i + 1])            # scalar path
    np.testing.assert_allclose(a.pull([0, 1, 2, 3]),
                               b.pull([0, 1, 2, 3]), rtol=1e-5,
                               atol=1e-6)


def test_ssd_table_batch_larger_than_budget():
    from paddle_tpu.distributed.ps.ssd_table import SsdSparseTable

    t = SsdSparseTable(4, {"rule": "sgd", "lr": 0.1}, seed=0,
                       max_mem_rows=4)
    ids = list(range(10))                      # batch > budget
    rows = t.pull(ids)
    assert rows.shape == (10, 4)
    t.push(ids, np.ones((10, 4), np.float32))
    again = t.pull(ids)
    np.testing.assert_allclose(again, rows - 0.1, rtol=1e-5)
    assert t.mem_rows <= 4 + 0  # budget restored after the access


# ---------------------------------------------------------------- round 4 --

def test_elastic_scanner_survives_transient_publish_failure():
    # advisor r4 (medium): a transient store error during the generation
    # publish must not kill the master's role thread — the node's
    # heartbeat keeps running, so standbys would defer to a wedged
    # master forever. The publish is now guarded and retried.
    import socket
    import threading

    from paddle_tpu.distributed.elastic import ElasticManager
    from paddle_tpu.distributed.store import TCPStore

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    host = TCPStore("127.0.0.1", port, is_master=True, world_size=1)
    try:
        class FlakyStore:
            """Raises TimeoutError on the FIRST generation publish."""

            def __init__(self, inner):
                self._inner = inner
                self.failures = 0

            def add(self, key, n):
                if key == "elastic/gen" and self.failures == 0:
                    self.failures += 1
                    raise TimeoutError("injected transient store timeout")
                return self._inner.add(key, n)

            def __getattr__(self, name):
                return getattr(self._inner, name)

        st = FlakyStore(TCPStore("127.0.0.1", port, is_master=False))
        mgr = ElasticManager(st, "node0", is_master=True,
                             heartbeat_interval=0.1,
                             heartbeat_timeout=1.0, min_nodes=1)
        result = {}

        def run():
            result["gen"] = mgr.start()

        t = threading.Thread(target=run, daemon=True)
        t.start()
        t.join(timeout=15.0)
        try:
            # start() returns only once a generation containing node0 is
            # published — which requires the scanner to have survived
            # the injected publish failure and retried
            assert not t.is_alive(), "scanner died on transient error"
            assert st.failures == 1
            gen, members = result["gen"]
            assert gen >= 1 and "node0" in members
        finally:
            mgr.stop()
    finally:
        host.close()


def test_register_plugin_does_not_receive_control_flag(monkeypatch):
    # advisor r4 (low): reinitialize_backends is our control flag; it
    # must be stripped from the options forwarded to the PJRT plugin
    from jax._src import xla_bridge as xb

    from paddle_tpu.device import custom

    seen = {}

    def fake_register(name, library_path=None, options=None):
        seen["options"] = options

    monkeypatch.setattr(xb, "register_plugin", fake_register)
    # device_type "cpu" passes the post-load platform check in a CPU
    # test process, so clear_backends is never reached
    custom.register_custom_device("cpu",
                                  library_path="/nonexistent.so",
                                  options={"reinitialize_backends": True,
                                           "vendor_opt": 7})
    try:
        assert seen["options"] == {"vendor_opt": 7}
    finally:
        custom._registry.pop("cpu", None)


def test_set_device_returns_custom_place():
    # advisor r4 (low): set_device('mychip:0') must return a CustomPlace
    # carrying the registered type, like the reference's core.CustomPlace
    from paddle_tpu.device import custom
    from paddle_tpu.device.custom import CustomPlace

    custom.register_custom_device("mychip_ap", alias_of="cpu")
    try:
        place = paddle.set_device("mychip_ap:0")
        assert isinstance(place, CustomPlace)
        assert place.get_device_type() == "mychip_ap"
        assert place.get_device_id() == 0
    finally:
        custom._registry.pop("mychip_ap", None)
        paddle.set_device("cpu")

"""paddle.incubate.nn.functional fused-op parity tests (reference surface
``python/paddle/incubate/nn/functional/``; numerics checked against unfused
compositions, the reference's own fused-kernel test strategy)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.incubate.nn.functional as FI


def _t(shape, seed=0, dtype="float32"):
    return paddle.to_tensor(
        np.random.default_rng(seed).normal(size=shape).astype(dtype))


def test_fused_rms_norm():
    x = _t((4, 16, 64), 1)
    w = _t((64,), 2)
    out, res = FI.fused_rms_norm(x, w, None, 1e-6, -1)
    xv = x.numpy().astype(np.float64)
    ms = np.mean(xv * xv, axis=-1, keepdims=True)
    ref = xv / np.sqrt(ms + 1e-6) * w.numpy()
    np.testing.assert_allclose(out.numpy(), ref, atol=1e-4)
    np.testing.assert_allclose(res.numpy(), x.numpy())


def test_fused_rms_norm_residual_bias():
    x = _t((2, 8, 32), 1)
    r = _t((2, 8, 32), 2)
    b = _t((32,), 3)
    w = _t((32,), 4)
    out, res = FI.fused_rms_norm(x, w, None, 1e-6, -1, bias=b, residual=r)
    v = x.numpy() + b.numpy() + r.numpy()
    np.testing.assert_allclose(res.numpy(), v, atol=1e-5)
    ms = np.mean(v * v, axis=-1, keepdims=True)
    np.testing.assert_allclose(out.numpy(), v / np.sqrt(ms + 1e-6) * w.numpy(),
                               atol=1e-4)


def test_fused_layer_norm():
    x = _t((3, 7, 48), 5)
    w = _t((48,), 6)
    b = _t((48,), 7)
    out, res = FI.fused_layer_norm(x, w, b, 1e-5, begin_norm_axis=-1)
    v = x.numpy().astype(np.float64)
    mean = v.mean(-1, keepdims=True)
    var = v.var(-1, keepdims=True)
    ref = (v - mean) / np.sqrt(var + 1e-5) * w.numpy() + b.numpy()
    np.testing.assert_allclose(out.numpy(), ref, atol=1e-4)


def test_fused_rope_roundtrip_grad():
    q = _t((2, 16, 4, 32), 8)
    k = _t((2, 16, 4, 32), 9)
    q.stop_gradient = False
    out_q, out_k, _ = FI.fused_rotary_position_embedding(q, k)
    assert tuple(out_q.shape) == (2, 16, 4, 32)
    # rotation preserves pairwise norms
    def pair_norm(a, neox=True):
        a = a.reshape(a.shape[0], a.shape[1], a.shape[2], -1, 2)
        return np.sqrt((a ** 2).sum(-1))
    np.testing.assert_allclose(
        pair_norm(out_q.numpy()), pair_norm(q.numpy()), atol=1e-4)
    (out_q.sum()).backward()
    assert q.grad is not None


def test_fused_rope_half_style():
    q = _t((1, 8, 2, 16), 10)
    out_q, _, _ = FI.fused_rotary_position_embedding(
        q, use_neox_rotary_style=False)
    d = 16
    inv = 1.0 / 10000.0 ** (np.arange(0, d // 2) * 2.0 / d)
    ang = np.arange(8)[:, None] * inv[None, :]
    ang = np.concatenate([ang, ang], -1)
    cos, sin = np.cos(ang), np.sin(ang)
    xv = q.numpy()
    x1, x2 = xv[..., : d // 2], xv[..., d // 2:]
    rot = np.concatenate([-x2, x1], -1)
    ref = xv * cos[None, :, None, :] + rot * sin[None, :, None, :]
    np.testing.assert_allclose(out_q.numpy(), ref, atol=1e-4)


def test_fused_rms_norm_norm_bias_no_residual():
    # regression: norm_bias without residual used to IndexError
    x = _t((2, 4, 32), 20)
    w = _t((32,), 21)
    nb = _t((32,), 22)
    out, _ = FI.fused_rms_norm(x, w, nb, 1e-6, -1)
    v = x.numpy()
    ms = np.mean(v * v, -1, keepdims=True)
    ref = v / np.sqrt(ms + 1e-6) * w.numpy() + nb.numpy()
    np.testing.assert_allclose(out.numpy(), ref, atol=1e-4)


def test_fused_rope_explicit_tables():
    # regression: user-supplied sin/cos used to be swapped
    q = _t((1, 8, 2, 16), 23)
    d, s = 16, 8
    inv = 1.0 / 10000.0 ** (np.arange(0, d // 2) * 2.0 / d)
    ang = np.repeat(np.arange(s)[:, None] * inv[None, :], 2, -1)
    sin = paddle.to_tensor(np.sin(ang).astype("float32"))
    cos = paddle.to_tensor(np.cos(ang).astype("float32"))
    out_explicit, _, _ = FI.fused_rotary_position_embedding(
        q, sin=sin, cos=cos)
    out_default, _, _ = FI.fused_rotary_position_embedding(q)
    np.testing.assert_allclose(out_explicit.numpy(), out_default.numpy(),
                               atol=1e-5)


def test_fused_rope_position_ids_beyond_seq():
    # regression: default tables with position ids >= seq_len gave NaN
    q = _t((1, 6, 2, 16), 26)
    pid = paddle.to_tensor((np.arange(6) + 4).astype("int32")[None])
    out, _, _ = FI.fused_rotary_position_embedding(q, position_ids=pid)
    assert np.isfinite(out.numpy()).all()
    # must equal slicing a longer sequence at those positions
    q10_np = np.zeros((1, 10, 2, 16), "float32")
    q10_np[:, 4:10] = q.numpy()
    out10, _, _ = FI.fused_rotary_position_embedding(
        paddle.to_tensor(q10_np))
    np.testing.assert_allclose(out.numpy(), out10.numpy()[:, 4:10],
                               atol=1e-5)


def test_fused_rope_position_ids():
    # per-example position ids must rotate each batch row by its own table
    q = _t((2, 6, 2, 16), 24)
    pid = paddle.to_tensor(
        np.stack([np.arange(6), np.arange(6) + 4]).astype("int32"))
    out, _, _ = FI.fused_rotary_position_embedding(q, position_ids=pid)
    # row 1 must differ from what row-0 positions would give it
    out_row0_pos, _, _ = FI.fused_rotary_position_embedding(
        q, position_ids=paddle.to_tensor(
            np.stack([np.arange(6), np.arange(6)]).astype("int32")))
    assert not np.allclose(out.numpy()[1], out_row0_pos.numpy()[1])
    np.testing.assert_allclose(out.numpy()[0], out_row0_pos.numpy()[0],
                               atol=1e-6)


def test_recompute_plain_callable_grads():
    # regression: recompute(lambda) used to drop parameter grads
    import paddle_tpu.nn as nn
    from paddle_tpu.distributed.fleet.recompute import recompute
    paddle.seed(0)
    lin = nn.Linear(8, 8)
    x = _t((4, 8), 25)
    y = recompute(lambda t: lin(t), x)
    y.sum().backward()
    assert lin.weight.grad is not None
    ref = lin(x)
    np.testing.assert_allclose(y.numpy(), ref.numpy(), atol=1e-5)


def test_swiglu():
    x = _t((4, 32), 11)
    out = FI.swiglu(x)
    a, b = np.split(x.numpy(), 2, axis=-1)
    silu = a / (1 + np.exp(-a)) * b
    np.testing.assert_allclose(out.numpy(), silu, atol=1e-5)
    y = _t((4, 32), 12)
    out2 = FI.swiglu(x, y)
    xv = x.numpy()
    np.testing.assert_allclose(out2.numpy(),
                               xv / (1 + np.exp(-xv)) * y.numpy(), atol=1e-5)


def test_fused_matmul_bias_linear_activation():
    x = _t((4, 8), 13)
    w = _t((8, 16), 14)
    b = _t((16,), 15)
    out = FI.fused_matmul_bias(x, w, b)
    np.testing.assert_allclose(out.numpy(), x.numpy() @ w.numpy() + b.numpy(),
                               atol=1e-4)
    out2 = FI.fused_linear_activation(x, w, b, activation="relu")
    np.testing.assert_allclose(
        out2.numpy(), np.maximum(x.numpy() @ w.numpy() + b.numpy(), 0),
        atol=1e-4)


def test_fused_dropout_add_eval():
    x = _t((4, 8), 16)
    y = _t((4, 8), 17)
    out = FI.fused_dropout_add(x, y, p=0.5, training=False)
    np.testing.assert_allclose(out.numpy(), x.numpy() + y.numpy(), atol=1e-6)


def test_fused_transformer_layers():
    """incubate.nn layer classes (reference fused_transformer.py:278,564):
    attention+FFN block trains under jit; dropout-add identity at p=0."""
    import numpy as np

    from paddle_tpu.incubate.nn import (FusedDropoutAdd, FusedFeedForward,
                                        FusedLinear, FusedMultiHeadAttention)

    paddle.seed(0)
    x = paddle.to_tensor(np.random.default_rng(0).normal(
        size=(2, 8, 16)).astype("float32"))
    attn = FusedMultiHeadAttention(16, 4, dropout_rate=0.0,
                                   attn_dropout_rate=0.0)
    ffn = FusedFeedForward(16, 32, dropout_rate=0.0, activation="gelu",
                           normalize_before=True)
    opt = paddle.optimizer.Adam(
        learning_rate=1e-2,
        parameters=list(attn.parameters()) + list(ffn.parameters()))
    tgt = paddle.zeros([2, 8, 16])

    @paddle.jit.to_static
    def step(x):
        loss = ((ffn(attn(x)) - tgt) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    losses = [float(step(x)) for _ in range(6)]
    assert losses[-1] < losses[0]
    assert tuple(FusedLinear(16, 8)(x).shape) == (2, 8, 8)
    fd = FusedDropoutAdd(p=0.0)
    np.testing.assert_allclose(fd(x, x).numpy(), 2 * x.numpy(), rtol=1e-6)

"""Table-driven op coverage through the OpTest harness (the analog of the
reference's ~1300 ``test_*_op.py`` files built on ``op_test.py:420``).

Each family runs: eager forward vs numpy, jit forward vs numpy, bfloat16
at loose tolerance, and (where listed) tape-vs-numeric gradients."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import ops
from paddle_tpu.testing import OpSpec, run_op_specs

R = np.random.default_rng(7)


def f32(*shape, lo=-2.0, hi=2.0):
    return (R.uniform(lo, hi, shape)).astype("float32")


def pos(*shape, lo=0.1, hi=3.0):
    return R.uniform(lo, hi, shape).astype("float32")


def i32(*shape, lo=0, hi=8):
    return R.integers(lo, hi, shape).astype("int32")


def test_unary_math_ops():
    import scipy.special as sp
    x = f32(3, 4)
    p = pos(3, 4)
    u = f32(3, 4, lo=-0.9, hi=0.9)
    specs = [
        OpSpec("abs", ops.abs, np.abs, [x], grad=(0,)),
        OpSpec("exp", ops.exp, np.exp, [x], grad=(0,)),
        OpSpec("expm1", ops.expm1, np.expm1, [x]),
        OpSpec("log", ops.log, np.log, [p], grad=(0,)),
        OpSpec("log2", ops.log2, np.log2, [p]),
        OpSpec("log10", ops.log10, np.log10, [p]),
        OpSpec("log1p", ops.log1p, np.log1p, [p]),
        OpSpec("sqrt", ops.sqrt, np.sqrt, [p], grad=(0,)),
        OpSpec("rsqrt", ops.rsqrt, lambda a: 1 / np.sqrt(a), [p]),
        OpSpec("square", ops.square, np.square, [x], grad=(0,)),
        OpSpec("reciprocal", ops.reciprocal, lambda a: 1 / a, [p]),
        OpSpec("sin", ops.sin, np.sin, [x], grad=(0,)),
        OpSpec("cos", ops.cos, np.cos, [x], grad=(0,)),
        OpSpec("tan", ops.tan, np.tan, [u]),
        OpSpec("asin", ops.asin, np.arcsin, [u]),
        OpSpec("acos", ops.acos, np.arccos, [u]),
        OpSpec("atan", ops.atan, np.arctan, [x]),
        OpSpec("sinh", ops.sinh, np.sinh, [x]),
        OpSpec("cosh", ops.cosh, np.cosh, [x]),
        OpSpec("tanh", ops.tanh, np.tanh, [x], grad=(0,)),
        OpSpec("asinh", ops.asinh, np.arcsinh, [x]),
        OpSpec("acosh", ops.acosh, np.arccosh, [pos(3, 4, lo=1.1)]),
        OpSpec("atanh", ops.atanh, np.arctanh, [u]),
        OpSpec("ceil", ops.ceil, np.ceil, [x], bf16=False),
        OpSpec("floor", ops.floor, np.floor, [x], bf16=False),
        OpSpec("round", ops.round, np.round, [x], bf16=False),
        OpSpec("trunc", ops.trunc, np.trunc, [x], bf16=False),
        OpSpec("sign", ops.sign, np.sign, [x], bf16=False),
        OpSpec("neg", ops.neg, np.negative, [x]),
        OpSpec("frac", ops.frac, lambda a: a - np.trunc(a), [x], bf16=False),
        OpSpec("erf", ops.erf, sp.erf, [x], grad=(0,)),
        OpSpec("erfinv", ops.erfinv, sp.erfinv, [u]),
        OpSpec("lgamma", ops.lgamma, sp.gammaln, [p]),
        OpSpec("digamma", ops.digamma, sp.digamma, [p]),
        OpSpec("i0", ops.i0, sp.i0, [x]),
        OpSpec("i1", ops.i1, sp.i1, [x]),
        OpSpec("deg2rad", ops.deg2rad, np.deg2rad, [x]),
        OpSpec("rad2deg", ops.rad2deg, np.rad2deg, [x]),
        OpSpec("angle", ops.angle, np.angle, [x]),
        OpSpec("nan_to_num", ops.nan_to_num, np.nan_to_num,
               [np.array([[np.nan, 1.0, np.inf, -np.inf]], "float32")]),
        OpSpec("clip", ops.clip, lambda a, min, max: np.clip(a, min, max),
               [x], {"min": -0.5, "max": 0.5}),
        OpSpec("scale", ops.scale,
               lambda a, scale, bias: a * scale + bias, [x],
               {"scale": 2.0, "bias": 0.5}, grad=(0,)),
        OpSpec("stanh", ops.stanh,
               lambda a, scale_a=0.67, scale_b=1.7159:
               scale_b * np.tanh(scale_a * a), [x]),
    ]
    run_op_specs(specs)


def test_binary_math_ops():
    x, y = f32(3, 4), f32(3, 4)
    p, q = pos(3, 4), pos(3, 4)
    specs = [
        OpSpec("add", ops.add, np.add, [x, y], grad=(0, 1)),
        OpSpec("subtract", ops.subtract, np.subtract, [x, y], grad=(0, 1)),
        OpSpec("multiply", ops.multiply, np.multiply, [x, y], grad=(0, 1)),
        OpSpec("divide", ops.divide, np.divide, [x, q], grad=(0, 1)),
        OpSpec("pow", ops.pow, lambda a, y: np.power(a, y), [p],
               {"y": 2.0}, grad=(0,)),
        OpSpec("maximum", ops.maximum, np.maximum, [x, y]),
        OpSpec("minimum", ops.minimum, np.minimum, [x, y]),
        OpSpec("fmax", ops.fmax, np.fmax, [x, y]),
        OpSpec("fmin", ops.fmin, np.fmin, [x, y]),
        OpSpec("mod", ops.mod, np.mod, [x, q]),
        OpSpec("floor_divide", ops.floor_divide, np.floor_divide, [x, q]),
        OpSpec("atan2", ops.atan2, np.arctan2, [x, y]),
        OpSpec("hypot", ops.hypot, np.hypot, [x, y]),
        OpSpec("copysign", ops.copysign, np.copysign, [x, y]),
        OpSpec("heaviside", ops.heaviside, np.heaviside, [x, y]),
        OpSpec("nextafter", ops.nextafter, np.nextafter, [x, y],
               bf16=False),
        OpSpec("logaddexp", ops.logaddexp, np.logaddexp, [x, y]),
        OpSpec("lerp", ops.lerp,
               lambda a, b, w: a + w * (b - a), [x, y, np.float32(0.3)],
               bf16=False),
        OpSpec("gcd", ops.gcd, np.gcd, [i32(3, 4, lo=1, hi=20),
                                        i32(3, 4, lo=1, hi=20)],
               bf16=False),
        OpSpec("lcm", ops.lcm, np.lcm, [i32(3, 4, lo=1, hi=10),
                                        i32(3, 4, lo=1, hi=10)],
               bf16=False),
    ]
    run_op_specs(specs)


def test_reduction_ops():
    x = f32(3, 4, 5)
    specs = [
        OpSpec("sum", ops.sum, lambda a, axis=None: np.sum(a, axis), [x],
               {"axis": 1}, grad=(0,)),
        OpSpec("mean", ops.mean, lambda a, axis=None: np.mean(a, axis),
               [x], {"axis": 2}, grad=(0,)),
        OpSpec("max", ops.max, lambda a, axis=None: np.max(a, axis), [x],
               {"axis": 0}),
        OpSpec("min", ops.min, lambda a, axis=None: np.min(a, axis), [x],
               {"axis": 0}),
        OpSpec("prod", ops.prod, lambda a, axis=None: np.prod(a, axis),
               [f32(2, 3)], {"axis": 1}),
        OpSpec("std", ops.std, lambda a: np.std(a, ddof=1), [x],
               rtol=1e-4),
        OpSpec("var", ops.var, lambda a: np.var(a, ddof=1), [x],
               rtol=1e-4),
        OpSpec("median", ops.median, np.median, [f32(3, 5)]),
        OpSpec("nanmean", ops.nanmean, np.nanmean,
               [np.array([[1, np.nan, 3.0]], "float32")]),
        OpSpec("nansum", ops.nansum, np.nansum,
               [np.array([[1, np.nan, 3.0]], "float32")]),
        OpSpec("logsumexp", ops.logsumexp,
               lambda a: np.log(np.sum(np.exp(a))), [x], rtol=1e-4),
        OpSpec("amax", ops.amax, lambda a, axis=None: np.max(a, axis),
               [x], {"axis": 1}),
        OpSpec("amin", ops.amin, lambda a, axis=None: np.min(a, axis),
               [x], {"axis": 1}),
        OpSpec("count_nonzero", ops.count_nonzero,
               lambda a: np.count_nonzero(a),
               [np.array([[0, 1, 2, 0]], "float32")], bf16=False),
        OpSpec("cumsum", ops.cumsum,
               lambda a, axis=None: np.cumsum(a, axis), [x], {"axis": 1},
               grad=(0,)),
        OpSpec("cumprod", ops.cumprod,
               lambda a, dim=None: np.cumprod(a, dim), [f32(2, 3)],
               {"dim": 1}),
        OpSpec("logcumsumexp", ops.logcumsumexp,
               lambda a, axis=0:
               np.log(np.cumsum(np.exp(a.astype(np.float64)),
                                axis)).astype(np.float32),
               [x], {"axis": 1}, rtol=1e-4),
        OpSpec("quantile", ops.quantile,
               lambda a, q: np.quantile(a, q), [f32(3, 5)], {"q": 0.5},
               bf16=False),
    ]
    run_op_specs(specs)


def test_manipulation_ops():
    x = f32(3, 4, 5)
    specs = [
        OpSpec("reshape", ops.reshape,
               lambda a, shape: a.reshape(shape), [x],
               {"shape": [4, 15]}, grad=(0,)),
        OpSpec("transpose", ops.transpose,
               lambda a, perm: np.transpose(a, perm), [x],
               {"perm": [2, 0, 1]}, grad=(0,)),
        OpSpec("flatten", ops.flatten, lambda a: a.reshape(-1), [x]),
        OpSpec("squeeze", ops.squeeze, np.squeeze, [f32(3, 1, 4)]),
        OpSpec("unsqueeze", ops.unsqueeze,
               lambda a, axis: np.expand_dims(a, axis), [x], {"axis": 1}),
        OpSpec("flip", ops.flip, lambda a, axis: np.flip(a, axis), [x],
               {"axis": 1}),
        OpSpec("roll", ops.roll,
               lambda a, shifts, axis: np.roll(a, shifts, axis), [x],
               {"shifts": 2, "axis": 1}),
        OpSpec("rot90", ops.rot90, lambda a: np.rot90(a), [f32(3, 4)]),
        OpSpec("tile", ops.tile,
               lambda a, repeat_times: np.tile(a, repeat_times), [x],
               {"repeat_times": [2, 1, 1]}),
        OpSpec("broadcast_to", ops.broadcast_to,
               lambda a, shape: np.broadcast_to(a, shape), [f32(1, 4)],
               {"shape": [3, 4]}),
        OpSpec("moveaxis", ops.moveaxis,
               lambda a, source, destination:
               np.moveaxis(a, source, destination), [x],
               {"source": 0, "destination": 2}),
        OpSpec("swapaxes", ops.swapaxes,
               lambda a, axis0, axis1: np.swapaxes(a, axis0, axis1), [x],
               {"axis0": 0, "axis1": 2}),
        OpSpec("t", ops.t, np.transpose, [f32(3, 4)]),
        OpSpec("tril", ops.tril, np.tril, [f32(4, 4)]),
        OpSpec("triu", ops.triu, np.triu, [f32(4, 4)]),
        OpSpec("diag", ops.diag, np.diag, [f32(4, 4)]),
        OpSpec("diagonal", ops.diagonal,
               lambda a: np.diagonal(a, 0, 0, 1), [f32(4, 4)]),
        OpSpec("trace", ops.trace, np.trace, [f32(4, 4)]),
        OpSpec("kron", ops.kron, np.kron, [f32(2, 2), f32(3, 3)]),
        OpSpec("repeat_interleave", ops.repeat_interleave,
               lambda a, repeats, axis: np.repeat(a, repeats, axis), [x],
               {"repeats": 2, "axis": 1}),
        OpSpec("take_along_axis", ops.take_along_axis,
               lambda a, idx, axis: np.take_along_axis(a, idx, axis),
               [f32(3, 5), R.integers(0, 5, (3, 2)).astype("int64")],
               {"axis": 1}, bf16=False),
        OpSpec("gather", ops.gather,
               lambda a, idx, axis=0: np.take(a, idx, axis),
               [f32(5, 3), np.array([0, 2, 4], "int64")], bf16=False),
        OpSpec("index_select", ops.index_select,
               lambda a, index, axis=0: np.take(a, index, axis),
               [f32(5, 3), np.array([1, 3], "int64")], {"axis": 0},
               bf16=False),
        OpSpec("masked_select", ops.masked_select,
               lambda a, m: a[m],
               [f32(3, 4), R.uniform(size=(3, 4)) > 0.5], bf16=False,
               jit=False),  # dynamic output shape: host-side op
        OpSpec("where", ops.where,
               lambda c, a, b: np.where(c, a, b),
               [R.uniform(size=(3, 4)) > 0.5, f32(3, 4), f32(3, 4)],
               bf16=False),
        OpSpec("concat", lambda a, b, **kw: ops.concat([a, b], **kw),
               lambda a, b, axis=0: np.concatenate([a, b], axis),
               [f32(2, 3), f32(2, 3)], {"axis": 1}),
        OpSpec("stack", lambda a, b, **kw: ops.stack([a, b], **kw),
               lambda a, b, axis=0: np.stack([a, b], axis),
               [f32(2, 3), f32(2, 3)], {"axis": 0}),
        OpSpec("split", lambda a: ops.split(a, 2, axis=1),
               lambda a: np.split(a, 2, axis=1), [f32(2, 4)]),
        OpSpec("chunk", lambda a: ops.chunk(a, 2, axis=0),
               lambda a: np.split(a, 2, axis=0), [f32(4, 3)]),
        OpSpec("unbind", lambda a: ops.unbind(a, axis=0),
               lambda a: list(a), [f32(3, 4)]),
        OpSpec("unstack", lambda a: ops.unstack(a, axis=0),
               lambda a: list(a), [f32(3, 4)]),
        OpSpec("pad", ops.pad,
               lambda a, pad: np.pad(a, [(0, 0), (1, 2)]),
               [f32(2, 3)], {"pad": [1, 2]}),
        OpSpec("one_hot", ops.one_hot,
               lambda a, num_classes: np.eye(num_classes,
                                             dtype=np.float32)[a],
               [np.array([0, 2, 1], "int64")], {"num_classes": 3},
               bf16=False),
    ]
    run_op_specs(specs)


def test_logic_compare_ops():
    x, y = f32(3, 4), f32(3, 4)
    b1 = R.uniform(size=(3, 4)) > 0.5
    b2 = R.uniform(size=(3, 4)) > 0.5
    ii = i32(3, 4)
    specs = [
        OpSpec("equal", ops.equal, np.equal, [x, x], bf16=False),
        OpSpec("not_equal", ops.not_equal, np.not_equal, [x, y],
               bf16=False),
        OpSpec("less_than", ops.less_than, np.less, [x, y], bf16=False),
        OpSpec("less_equal", ops.less_equal, np.less_equal, [x, y],
               bf16=False),
        OpSpec("greater_than", ops.greater_than, np.greater, [x, y],
               bf16=False),
        OpSpec("greater_equal", ops.greater_equal, np.greater_equal,
               [x, y], bf16=False),
        OpSpec("logical_and", ops.logical_and, np.logical_and, [b1, b2],
               bf16=False),
        OpSpec("logical_or", ops.logical_or, np.logical_or, [b1, b2],
               bf16=False),
        OpSpec("logical_xor", ops.logical_xor, np.logical_xor, [b1, b2],
               bf16=False),
        OpSpec("logical_not", ops.logical_not, np.logical_not, [b1],
               bf16=False),
        OpSpec("bitwise_and", ops.bitwise_and, np.bitwise_and, [ii, ii],
               bf16=False),
        OpSpec("bitwise_or", ops.bitwise_or, np.bitwise_or, [ii, ii],
               bf16=False),
        OpSpec("bitwise_xor", ops.bitwise_xor, np.bitwise_xor, [ii, ii],
               bf16=False),
        OpSpec("bitwise_not", ops.bitwise_not, np.bitwise_not, [ii],
               bf16=False),
        OpSpec("isnan", ops.isnan, np.isnan,
               [np.array([1.0, np.nan], "float32")], bf16=False),
        OpSpec("isinf", ops.isinf, np.isinf,
               [np.array([1.0, np.inf], "float32")], bf16=False),
        OpSpec("isfinite", ops.isfinite, np.isfinite,
               [np.array([1.0, np.inf, np.nan], "float32")], bf16=False),
        OpSpec("maximum_int", ops.maximum, np.maximum, [ii, ii],
               bf16=False),
    ]
    run_op_specs(specs)


def test_linalg_ops():
    a = f32(3, 3) + 3 * np.eye(3, dtype="float32")  # well-conditioned
    x, y = f32(3, 4), f32(4, 5)
    specs = [
        OpSpec("matmul", ops.matmul, lambda p, q: p @ q, [x, y],
               grad=(0, 1), grad_atol=2e-2),
        OpSpec("mm", ops.mm, lambda p, q: p @ q, [x, y]),
        OpSpec("bmm", ops.bmm, lambda p, q: p @ q,
               [f32(2, 3, 4), f32(2, 4, 5)]),
        OpSpec("dot", ops.dot, np.dot, [f32(5), f32(5)]),
        OpSpec("mv", ops.mv, lambda m, v: m @ v, [f32(3, 4), f32(4)]),
        OpSpec("outer", ops.outer, np.outer, [f32(3), f32(4)]),
        OpSpec("inner", ops.inner, np.inner, [f32(3), f32(3)]),
        OpSpec("cross", ops.cross, lambda p, q: np.cross(p, q),
               [f32(3), f32(3)]),
        OpSpec("det", ops.det, np.linalg.det, [a], rtol=1e-4,
               bf16=False),
        OpSpec("inverse", ops.inverse, np.linalg.inv, [a], rtol=1e-3,
               atol=1e-4, bf16=False),
        OpSpec("norm", ops.norm, lambda m: np.linalg.norm(m), [x],
               rtol=1e-4),
        OpSpec("matrix_power", ops.matrix_power,
               lambda m, n: np.linalg.matrix_power(m, n), [a], {"n": 2},
               rtol=1e-4, bf16=False),
        OpSpec("solve", ops.solve, np.linalg.solve, [a, f32(3, 2)],
               rtol=1e-3, atol=1e-4, bf16=False),
        OpSpec("slogdet", ops.slogdet,
               lambda m: np.stack(np.linalg.slogdet(m)), [a], rtol=1e-4,
               bf16=False),
        OpSpec("multi_dot", lambda p, q, r: ops.multi_dot([p, q, r]),
               lambda p, q, r: p @ q @ r,
               [f32(2, 3), f32(3, 4), f32(4, 2)], rtol=1e-4),
        OpSpec("einsum", lambda p, q: ops.einsum("ij,jk->ik", p, q),
               lambda p, q: p @ q, [x, y], rtol=1e-4),
        OpSpec("tensordot", ops.tensordot,
               lambda p, q, axes=2: np.tensordot(p, q, axes),
               [f32(2, 3, 4), f32(3, 4, 5)], rtol=1e-4),
    ]
    run_op_specs(specs)


def test_search_sort_ops():
    x = f32(3, 5)
    specs = [
        OpSpec("argmax", ops.argmax,
               lambda a, axis=None: np.argmax(a, axis), [x], {"axis": 1},
               bf16=False),
        OpSpec("argmin", ops.argmin,
               lambda a, axis=None: np.argmin(a, axis), [x], {"axis": 1},
               bf16=False),
        OpSpec("argsort", ops.argsort,
               lambda a, axis=-1: np.argsort(a, axis), [x], bf16=False),
        OpSpec("sort", ops.sort, lambda a, axis=-1: np.sort(a, axis),
               [x]),
        OpSpec("topk", lambda a: ops.topk(a, 2),
               lambda a: (np.sort(a, -1)[:, ::-1][:, :2],
                          np.argsort(-a, -1)[:, :2]), [x], bf16=False),
        OpSpec("searchsorted", ops.searchsorted, np.searchsorted,
               [np.sort(f32(8)), f32(4)], bf16=False),
        OpSpec("nonzero", ops.nonzero,
               lambda a: np.stack(np.nonzero(a), -1),
               [np.array([[0, 1], [2, 0]], "float32")], bf16=False,
               jit=False),
        OpSpec("unique", lambda a: ops.unique(a),
               lambda a: np.unique(a),
               [np.array([3, 1, 2, 1, 3], "float32")], bf16=False,
               jit=False),
        OpSpec("kthvalue", lambda a: ops.kthvalue(a, 2),
               lambda a: (np.sort(a, -1)[:, 1],
                          np.argsort(a, -1)[:, 1]), [x], bf16=False),
        OpSpec("mode", lambda a: ops.mode(a),
               lambda a: _np_mode(a),
               [np.array([[1, 2, 2], [3, 3, 1]], "float32")], bf16=False),
        OpSpec("bincount", ops.bincount, np.bincount,
               [np.array([0, 1, 1, 3], "int64")], bf16=False,
               jit=False),
        OpSpec("histogram", lambda a: ops.histogram(a, bins=4, min=0,
                                                    max=4),
               lambda a: np.histogram(a, bins=4, range=(0, 4))[0],
               [np.array([0.5, 1.5, 1.7, 3.2], "float32")], bf16=False),
    ]
    run_op_specs(specs)


def _np_mode(a):
    vals = []
    idxs = []
    for row in a:
        uniq, counts = np.unique(row, return_counts=True)
        best = uniq[np.argmax(counts)]
        # paddle mode returns the LAST index of the mode value
        idx = np.where(row == best)[0][-1]
        vals.append(best)
        idxs.append(idx)
    return np.asarray(vals, a.dtype), np.asarray(idxs, np.int64)

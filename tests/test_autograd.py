"""Autograd engine tests with numeric-gradient checks — the OpTest
check_grad discipline (reference test/legacy_test/op_test.py:2975, SURVEY §4)
applied to the tape engine."""
import numpy as np
import pytest

import paddle_tpu as pp


def numeric_grad(fn, x, eps=1e-2):
    """Central-difference gradient of scalar fn at numpy array x."""
    x = np.asarray(x, np.float64)
    g = np.zeros_like(x)
    it = np.nditer(x, flags=["multi_index"])
    while not it.finished:
        i = it.multi_index
        xp, xm = x.copy(), x.copy()
        xp[i] += eps
        xm[i] -= eps
        g[i] = (fn(xp) - fn(xm)) / (2 * eps)
        it.iternext()
    return g


def check_grad(op, x_np, rtol=1e-2, atol=1e-3):
    x = pp.to_tensor(x_np.astype("float32"), stop_gradient=False)
    y = op(x).sum()
    y.backward()
    num = numeric_grad(lambda v: float(np.sum(np.asarray(
        op(pp.to_tensor(v.astype("float32"))).numpy(), np.float64))), x_np)
    np.testing.assert_allclose(x.grad.numpy(), num, rtol=rtol, atol=atol)


@pytest.mark.parametrize("op,data", [
    (lambda x: x.exp(), np.array([[0.1, -0.5], [1.0, 0.3]])),
    (lambda x: x.tanh(), np.array([[0.1, -0.5], [1.0, 0.3]])),
    (lambda x: x.sigmoid() if hasattr(x, "sigmoid") else 1 / (1 + (-x).exp()),
     np.array([[0.2, -0.7]])),
    (lambda x: x.sqrt(), np.array([[0.5, 1.5], [2.0, 3.0]])),
    (lambda x: x.log(), np.array([[0.5, 1.5]])),
    (lambda x: x * x * x, np.array([[0.5, -1.5]])),
    (lambda x: x.abs(), np.array([[0.5, -1.5]])),
    (lambda x: pp.maximum(x, pp.zeros_like(x)), np.array([[0.5, -1.5]])),
    (lambda x: x.reshape([4]).cumsum(), np.array([[0.5, -1.5], [1.0, 2.0]])),
    (lambda x: pp.matmul(x, x, transpose_y=True), np.array([[0.5, -1.5], [1.0, 2.0]])),
    (lambda x: x.transpose([1, 0]) @ x, np.array([[0.5, -1.5], [1.0, 2.0]])),
    (lambda x: x[0:1, :] * 3, np.array([[0.5, -1.5], [1.0, 2.0]])),
    (lambda x: pp.concat([x, x * 2], axis=0), np.array([[0.5, -1.5]])),
    (lambda x: x.mean(axis=0), np.array([[0.5, -1.5], [1.0, 2.0]])),
    (lambda x: pp.where(x > pp.to_tensor(0.0), x * 2, x * 3),
     np.array([[0.5, -1.5]])),
    (lambda x: x.max(axis=1), np.array([[0.5, -1.5], [1.0, 2.0]])),
    (lambda x: x.norm(), np.array([[0.5, -1.5], [1.0, 2.0]])),
    (lambda x: pp.softmax(x, axis=-1) if hasattr(pp, "softmax") else x,
     np.array([[0.5, -1.5, 0.2]])),
])
def test_numeric_grads(op, data):
    check_grad(op, data)


def test_grad_accumulation():
    x = pp.to_tensor([1.0, 2.0], stop_gradient=False)
    (x * 2).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [2, 2])
    (x * 3).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [5, 5])
    x.clear_grad()
    assert x.grad is None


def test_diamond_graph():
    x = pp.to_tensor([2.0], stop_gradient=False)
    a = x * 3
    b = a * a + a  # a used twice
    b.sum().backward()
    # d/dx (9x^2 + 3x) = 18x + 3 = 39
    np.testing.assert_allclose(x.grad.numpy(), [39.0])


def test_stop_gradient_blocks():
    x = pp.to_tensor([1.0], stop_gradient=False)
    y = pp.to_tensor([2.0], stop_gradient=True)
    z = (x * y).sum()
    z.backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.0])
    assert y.grad is None


def test_detach():
    x = pp.to_tensor([1.0], stop_gradient=False)
    y = (x * 2).detach()
    assert y.stop_gradient
    z = (x * 2 + y).sum()
    z.backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.0])


def test_retain_graph():
    x = pp.to_tensor([1.0], stop_gradient=False)
    y = (x * x).sum()
    y.backward(retain_graph=True)
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [4.0])
    with pytest.raises(RuntimeError):
        y.backward()


def test_non_scalar_backward_needs_grad():
    x = pp.to_tensor([1.0, 2.0], stop_gradient=False)
    y = x * 2
    with pytest.raises(RuntimeError):
        y.backward()
    y.backward(pp.to_tensor([1.0, 1.0]))
    np.testing.assert_allclose(x.grad.numpy(), [2, 2])


def test_no_grad_context():
    x = pp.to_tensor([1.0], stop_gradient=False)
    with pp.no_grad():
        y = x * 2
    assert y.stop_gradient
    assert y._node is None


def test_grad_api_and_double_backward():
    x = pp.to_tensor([2.0], stop_gradient=False)
    y = (x ** 3).sum()
    (gx,) = pp.grad(y, x, create_graph=True)
    np.testing.assert_allclose(gx.numpy(), [12.0])
    assert not gx.stop_gradient
    (ggx,) = pp.grad(gx.sum(), x)
    np.testing.assert_allclose(ggx.numpy(), [12.0])  # d(3x^2)/dx = 6x


def test_backward_hook():
    x = pp.to_tensor([1.0], stop_gradient=False)
    seen = []

    def hook(g):
        seen.append(g.numpy().copy())
        return g * 10

    x.register_hook(hook)
    (x * 2).sum().backward()
    assert len(seen) == 1
    np.testing.assert_allclose(x.grad.numpy(), [20.0])


def test_retain_grads_intermediate():
    x = pp.to_tensor([1.0], stop_gradient=False)
    y = x * 2
    y.retain_grads()
    z = (y * 3).sum()
    z.backward()
    np.testing.assert_allclose(y.grad.numpy(), [3.0])


def test_multi_output_op_grad():
    x = pp.to_tensor(np.arange(6, dtype="float32").reshape(2, 3),
                     stop_gradient=False)
    parts = pp.split(x, 3, axis=1)
    loss = (parts[0] * 1 + parts[1] * 2 + parts[2] * 3).sum()
    loss.backward()
    np.testing.assert_allclose(x.grad.numpy(), [[1, 2, 3], [1, 2, 3]])


def test_partial_use_of_outputs():
    x = pp.to_tensor(np.ones((2, 2), np.float32), stop_gradient=False)
    a, b = pp.split(x, 2, axis=0)
    a.sum().backward()  # b unused -> zero cotangent branch
    np.testing.assert_allclose(x.grad.numpy(), [[1, 1], [0, 0]])


def test_int_output_no_grad():
    x = pp.to_tensor([3.0, 1.0], stop_gradient=False)
    v, i = pp.topk(x, 1)
    assert i.stop_gradient
    v.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [1, 0])


def test_setitem_grad():
    x = pp.to_tensor([1.0, 2.0, 3.0], stop_gradient=False)
    y = x * 2
    y[0] = 0.0
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [0, 2, 2])


def test_pylayer():
    from paddle_tpu.autograd import PyLayer

    class Double(PyLayer):
        @staticmethod
        def forward(ctx, a):
            ctx.save_for_backward(a)
            return a * 2

        @staticmethod
        def backward(ctx, g):
            (a,) = ctx.saved_tensor
            return g * 2

    x = pp.to_tensor([1.0, 2.0], stop_gradient=False)
    y = Double.apply(x)
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [2, 2])

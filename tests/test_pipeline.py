"""SPMD pipeline parallelism tests (virtual 8-device mesh).

Parity model mirrors the reference pipeline tests
(``test/collective/fleet/hybrid_parallel_pp_*.py``): the pipelined stack
must produce the same outputs/grads/losses as running the identical
weights sequentially on one device."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.distributed.fleet.pipeline import (LayerDesc,
                                                   PipelinedBlocks,
                                                   PipelineLayer)


@pytest.fixture(scope="module")
def mesh():
    return dist.ProcessMesh(np.arange(8).reshape(4, 2), ["pp", "dp"])


class Block(nn.Layer):
    def __init__(self, width=16):
        super().__init__()
        self.fc1 = nn.Linear(width, 2 * width)
        self.fc2 = nn.Linear(2 * width, width)

    def forward(self, x):
        return x + self.fc2(F.gelu(self.fc1(x)))


def _clone_to_eager(pipe, n_blocks):
    blocks = [Block() for _ in range(n_blocks)]
    for li, b in enumerate(blocks):
        for n, p in b.named_parameters():
            p._write(pipe.stacked_parameter(n)._read()[li])
    return blocks


def test_pipeline_fwd_bwd_parity(mesh):
    paddle.seed(0)
    pipe = PipelinedBlocks(Block, 8, mesh=mesh, pp_axis="pp",
                           num_microbatches=4)
    x = np.random.default_rng(0).normal(size=(8, 4, 16)).astype("float32")

    xt = paddle.to_tensor(x)
    xt.stop_gradient = False
    out = pipe(xt, batch_axes="dp")
    out.sum().backward()

    blocks = _clone_to_eager(pipe, 8)
    ref = paddle.to_tensor(x)
    ref.stop_gradient = False
    h = ref
    for b in blocks:
        h = b(h)
    h.sum().backward()

    np.testing.assert_allclose(np.asarray(out._read()),
                               np.asarray(h._read()), atol=1e-5)
    np.testing.assert_allclose(np.asarray(xt.grad._read()),
                               np.asarray(ref.grad._read()), atol=1e-5)
    for n in dict(blocks[0].named_parameters()):
        gs = np.asarray(pipe.stacked_parameter(n).grad._read())
        ge = np.stack([np.asarray(dict(b.named_parameters())[n]
                                  .grad._read()) for b in blocks])
        np.testing.assert_allclose(gs, ge, atol=1e-4)


def test_pipeline_layer_desc_api(mesh):
    paddle.seed(1)
    pl = PipelineLayer([LayerDesc(Block, 16) for _ in range(4)], mesh=mesh,
                       pp_axis="pp", num_microbatches=2)
    x = paddle.to_tensor(np.ones((4, 2, 16), "float32"))
    out = pl(x, batch_axes="dp")
    assert tuple(out.shape) == (4, 2, 16)
    with pytest.raises(NotImplementedError):
        PipelineLayer([LayerDesc(Block, 16), LayerDesc(Block, 32)],
                      mesh=mesh)


def test_gpt_pipe_train_step_parity(mesh):
    """jit-compiled pipelined GPT train step matches the plain GPT given
    identical weights."""
    from paddle_tpu.models.gpt import (GPTConfig, GPTForCausalLM,
                                       GPTForCausalLMPipe)

    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=4,
                    num_heads=4, max_seq_len=16, dropout=0.0)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 64, (4, 16)).astype(np.int32)
    labels = rng.integers(0, 64, (4, 16)).astype(np.int32)

    paddle.seed(0)
    pipe = GPTForCausalLMPipe(cfg, mesh, pp_axis="pp", dp_axis="dp",
                              num_microbatches=2)
    paddle.seed(0)
    ref = GPTForCausalLM(cfg)
    # copy pipe weights into the eager reference
    ref.gpt.wte.weight._write(pipe.wte.weight._read())
    ref.gpt.wpe.weight._write(pipe.wpe.weight._read())
    ref.gpt.ln_f.weight._write(pipe.ln_f.weight._read())
    ref.gpt.ln_f.bias._write(pipe.ln_f.bias._read())
    for li, blk in enumerate(ref.gpt.blocks):
        for n, p in blk.named_parameters():
            p._write(pipe.blocks.stacked_parameter(n)._read()[li])

    def train(model):
        model.train()
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=model.parameters())

        @paddle.jit.to_static
        def step(i, l):
            loss = model(i, l)
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss

        return [float(step(paddle.to_tensor(ids),
                           paddle.to_tensor(labels))) for _ in range(3)]

    losses_pipe = train(pipe)
    losses_ref = train(ref)
    np.testing.assert_allclose(losses_pipe, losses_ref, rtol=2e-4)

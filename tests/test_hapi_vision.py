"""hapi Model / metric / vision tests (reference test patterns:
``test/legacy_test/test_hapi_*`` — fit on a small dataset, metric
accumulate checks, model forward shapes)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.io import Dataset
from paddle_tpu.metric import Accuracy, Auc, Precision, Recall
from paddle_tpu.vision import models, transforms


class RandomClsDataset(Dataset):
    """Synthetic separable 2-class data."""

    def __init__(self, n=64, dim=16, classes=4, seed=0, centers_seed=42):
        self.centers = np.random.default_rng(centers_seed).normal(
            size=(classes, dim)).astype("float32") * 3
        rng = np.random.default_rng(seed)
        self.labels = rng.integers(0, classes, n).astype("int64")
        self.x = (self.centers[self.labels] +
                  rng.normal(size=(n, dim)).astype("float32") * 0.1)

    def __getitem__(self, i):
        return self.x[i], np.asarray([self.labels[i]], "int64")

    def __len__(self):
        return len(self.x)


def test_model_fit_evaluate_predict():
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 4))
    model = paddle.Model(net)
    model.prepare(
        optimizer=paddle.optimizer.Adam(learning_rate=0.01,
                                        parameters=net.parameters()),
        loss=nn.CrossEntropyLoss(),
        metrics=Accuracy())
    train = RandomClsDataset(n=64, seed=0)
    val = RandomClsDataset(n=32, seed=1)
    model.fit(train, epochs=3, batch_size=16, verbose=0)
    res = model.evaluate(val, batch_size=16, verbose=0)
    assert res["eval_acc"] > 0.9, res
    preds = model.predict(val, batch_size=16, stack_outputs=True)
    assert preds[0].shape == (32, 4)


def test_model_save_load(tmp_path):
    paddle.seed(1)
    net = nn.Sequential(nn.Linear(8, 3))
    model = paddle.Model(net)
    model.prepare(
        optimizer=paddle.optimizer.SGD(learning_rate=0.1,
                                       parameters=net.parameters()),
        loss=nn.CrossEntropyLoss())
    p = str(tmp_path / "ckpt")
    model.save(p)
    net2 = nn.Sequential(nn.Linear(8, 3))
    model2 = paddle.Model(net2)
    model2.prepare(
        optimizer=paddle.optimizer.SGD(learning_rate=0.1,
                                       parameters=net2.parameters()),
        loss=nn.CrossEntropyLoss())
    model2.load(p)
    x = paddle.to_tensor(np.ones((2, 8), "float32"))
    np.testing.assert_allclose(net(x).numpy(), net2(x).numpy(), atol=1e-6)


def test_early_stopping():
    paddle.seed(2)
    net = nn.Sequential(nn.Linear(16, 4))
    model = paddle.Model(net)
    model.prepare(
        optimizer=paddle.optimizer.SGD(learning_rate=0.0,  # never improves
                                       parameters=net.parameters()),
        loss=nn.CrossEntropyLoss(),
        metrics=Accuracy())
    es = paddle.callbacks.EarlyStopping(monitor="eval_loss", mode="min",
                                        patience=1, verbose=0,
                                        save_best_model=False)
    data = RandomClsDataset(n=32, seed=3)
    model.fit(data, eval_data=data, epochs=10, batch_size=16, verbose=0,
              callbacks=[es])
    assert model.stop_training


def test_accuracy_topk():
    m = Accuracy(topk=(1, 2))
    pred = paddle.to_tensor(np.asarray(
        [[0.1, 0.7, 0.2], [0.8, 0.1, 0.1]], "float32"))
    label = paddle.to_tensor(np.asarray([[2], [0]], "int64"))
    m.update(m.compute(pred, label))
    top1, top2 = m.accumulate()
    assert abs(top1 - 0.5) < 1e-6
    assert abs(top2 - 1.0) < 1e-6


def test_precision_recall_auc():
    p, r, a = Precision(), Recall(), Auc()
    preds = np.asarray([0.9, 0.8, 0.2, 0.6], "float32")
    labels = np.asarray([1, 0, 1, 1], "int64")
    p.update(preds, labels)
    r.update(preds, labels)
    a.update(preds, labels)
    assert abs(p.accumulate() - 2 / 3) < 1e-6
    assert abs(r.accumulate() - 2 / 3) < 1e-6
    assert 0.0 <= a.accumulate() <= 1.0


@pytest.mark.parametrize("factory,ch,size,classes", [
    (models.LeNet, 1, 28, 10),
    (lambda: models.resnet18(num_classes=7), 3, 32, 7),
    (lambda: models.mobilenet_v2(num_classes=5), 3, 32, 5),
])
def test_vision_models_forward(factory, ch, size, classes):
    paddle.seed(0)
    net = factory()
    net.eval()
    x = paddle.to_tensor(
        np.random.default_rng(0).normal(size=(2, ch, size, size))
        .astype("float32"))
    out = net(x)
    assert tuple(out.shape) == (2, classes)


def test_resnet50_bottleneck_shapes():
    paddle.seed(0)
    net = models.resnet50(num_classes=3)
    net.eval()
    x = paddle.to_tensor(np.zeros((1, 3, 64, 64), "float32"))
    assert tuple(net(x).shape) == (1, 3)
    # bottleneck expansion: layer1 output channels = 256
    assert net.layer1[0].conv3.weight.shape[0] == 256


def test_pretrained_rejected():
    with pytest.raises(ValueError, match="pretrained"):
        models.resnet18(pretrained=True)


def test_transforms_pipeline():
    t = transforms.Compose([
        transforms.Resize(36),
        transforms.CenterCrop(32),
        transforms.ToTensor(),
        transforms.Normalize(mean=[0.5, 0.5, 0.5], std=[0.5, 0.5, 0.5]),
    ])
    img = np.random.default_rng(0).integers(0, 255, (48, 64, 3), "uint8")
    out = t(img)
    assert out.shape == (3, 32, 32)
    assert out.dtype == np.float32
    assert -1.01 <= out.min() and out.max() <= 1.01


def test_transforms_resize_aspect():
    img = np.zeros((40, 80, 3), "uint8")
    out = transforms.resize(img, 20)
    assert out.shape[:2] == (20, 40)


def test_random_crop_pad():
    img = np.ones((10, 10, 1), "uint8")
    out = transforms.RandomCrop(8)(img)
    assert out.shape == (8, 8, 1)
    out2 = transforms.Pad(2)(img)
    assert out2.shape == (14, 14, 1)


def test_lenet_with_model_fit():
    """Config-1 class smoke: LeNet through the hapi surface (reference
    test_hapi pattern: Model(LeNet()).fit(MNIST))."""

    class FakeMNIST(Dataset):
        def __init__(self, n=32):
            rng = np.random.default_rng(0)
            self.x = rng.normal(size=(n, 1, 28, 28)).astype("float32")
            self.y = rng.integers(0, 10, n).astype("int64")

        def __getitem__(self, i):
            return self.x[i], np.asarray([self.y[i]], "int64")

        def __len__(self):
            return len(self.x)

    paddle.seed(0)
    net = models.LeNet()
    model = paddle.Model(net)
    model.prepare(
        optimizer=paddle.optimizer.Adam(learning_rate=1e-3,
                                        parameters=net.parameters()),
        loss=nn.CrossEntropyLoss(),
        metrics=Accuracy())
    model.fit(FakeMNIST(), epochs=1, batch_size=16, verbose=0)
    out = model.predict_batch([np.zeros((2, 1, 28, 28), "float32")])
    assert tuple(out.shape) == (2, 10)


def test_grad_accumulation_parity():
    """accumulate_grad_batches=k @ bs=b must match one step @ bs=k*b, and
    both accumulation step variants must compile (no eager fallback)."""
    import warnings

    def build():
        paddle.seed(0)
        return nn.Sequential(nn.Linear(16, 8), nn.ReLU(), nn.Linear(8, 4))

    ds = RandomClsDataset(n=32)
    net_a = build()
    ma = paddle.Model(net_a)
    ma.prepare(paddle.optimizer.SGD(learning_rate=0.1,
                                    parameters=net_a.parameters()),
               nn.CrossEntropyLoss())
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        ma.fit(ds, batch_size=4, epochs=2, shuffle=False, verbose=0,
               accumulate_grad_batches=4)
        assert not [x for x in w if "eager fallback" in str(x.message)]

    net_b = build()
    mb = paddle.Model(net_b)
    mb.prepare(paddle.optimizer.SGD(learning_rate=0.1,
                                    parameters=net_b.parameters()),
               nn.CrossEntropyLoss())
    mb.fit(ds, batch_size=16, epochs=2, shuffle=False, verbose=0)
    for pa, pb in zip(net_a.parameters(), net_b.parameters()):
        np.testing.assert_allclose(np.asarray(pa._read()),
                                   np.asarray(pb._read()), atol=1e-5)


def test_evaluate_without_loss():
    """Metrics-only prepare: no bogus eval_loss, metrics still reported."""
    net = nn.Linear(16, 4)
    m = paddle.Model(net)
    m.prepare(metrics=Accuracy())
    res = m.evaluate(RandomClsDataset(n=16), batch_size=8, verbose=0)
    assert "eval_loss" not in res and "eval_acc" in res


def test_predict_empty_dataset():
    class Empty(Dataset):
        def __len__(self):
            return 0

        def __getitem__(self, i):
            raise IndexError

    m = paddle.Model(nn.Linear(4, 2))
    m.prepare()
    assert m.predict(Empty(), verbose=0) == []


def test_auc_negative_preds_no_wraparound():
    a = Auc(num_thresholds=10)
    a.update(np.array([-0.5, 1.7, 0.9, 0.1]), np.array([0, 1, 1, 0]))
    assert a._stat_neg[0] == 1 and a._stat_pos[10] == 1
    assert 0.0 <= a.accumulate() <= 1.0


def test_accuracy_duplicate_topk():
    acc = Accuracy(topk=(1, 1))
    acc.update(np.array([[1.0, 0.0], [1.0, 0.0]]))
    assert acc.accumulate() == [1.0, 1.0]


def test_resize_rounds_not_truncates():
    img = np.full((4, 4, 1), 127, "uint8")
    img[::2] = 128  # interpolated values land at x.5 boundaries
    out = transforms.resize(img, (2, 2), "bilinear")
    assert out.dtype == np.uint8
    assert int(out.max()) >= 127  # truncation bias would pull everything down


def test_fit_window_matches_per_batch_fit():
    # fit(window=K) must produce the same training trajectory as the
    # per-batch loop: same batches, same scheduler steps, one scanned
    # launch per window (VERDICT r4 #4: WindowRunner shipped to users)
    from paddle_tpu.io import Dataset as DS

    class Reg(DS):
        def __init__(self):
            rng = np.random.default_rng(0)
            self.x = rng.normal(size=(33, 4)).astype(np.float32)
            w = np.array([[1.0], [-2.0], [3.0], [0.5]], np.float32)
            self.y = self.x @ w

        def __len__(self):
            return len(self.x)

        def __getitem__(self, i):
            return self.x[i], self.y[i]

    def build():
        paddle.seed(7)
        net = nn.Linear(4, 1)
        m = paddle.Model(net)
        sched = paddle.optimizer.lr.StepDecay(
            learning_rate=0.05, step_size=4, gamma=0.5)
        m.prepare(paddle.optimizer.SGD(learning_rate=sched,
                                       parameters=net.parameters()),
                  paddle.nn.loss.MSELoss())
        return m, net

    losses_a, losses_b = [], []

    class Rec(paddle.callbacks.Callback):
        def __init__(self, sink):
            self.sink = sink

        def on_train_batch_end(self, step, logs=None):
            self.sink.append(logs["loss"])

    m1, n1 = build()
    m1.fit(Reg(), epochs=2, batch_size=8, shuffle=False, verbose=0,
           callbacks=[Rec(losses_a)])
    m2, n2 = build()
    from paddle_tpu.jit.multi_step import WindowRunner
    runs = {"n": 0}
    orig_run = WindowRunner.run

    def counting_run(self, *a, **k):
        runs["n"] += 1
        return orig_run(self, *a, **k)

    WindowRunner.run = counting_run
    try:
        m2.fit(Reg(), epochs=2, batch_size=8, shuffle=False, verbose=0,
               window=3, callbacks=[Rec(losses_b)])
    finally:
        WindowRunner.run = orig_run

    assert len(losses_a) == len(losses_b) == 10  # 5 batches x 2 epochs
    np.testing.assert_allclose(losses_a, losses_b, rtol=2e-4, atol=1e-5)
    np.testing.assert_allclose(n1.weight.numpy(), n2.weight.numpy(),
                               rtol=2e-4, atol=1e-6)
    # the windowed run really used windows: epoch1 = plain prime +
    # window(3) + plain tail; epoch2 = window(3) + 2-step plain tail
    assert runs["n"] == 2, runs


def test_fit_window_respects_num_iters():
    from paddle_tpu.io import Dataset as DS

    class Reg(DS):
        def __len__(self):
            return 40

        def __getitem__(self, i):
            x = np.float32([i % 5, 1.0])
            return x, np.float32([i % 3])

    paddle.seed(0)
    net = nn.Linear(2, 1)
    m = paddle.Model(net)
    m.prepare(paddle.optimizer.SGD(learning_rate=0.01,
                                   parameters=net.parameters()),
              paddle.nn.loss.MSELoss())
    seen = []

    class Rec(paddle.callbacks.Callback):
        def on_train_batch_end(self, step, logs=None):
            seen.append(step)

    m.fit(Reg(), epochs=5, batch_size=4, shuffle=False, verbose=0,
          window=4, num_iters=7, callbacks=[Rec()])
    assert len(seen) == 7


def test_fit_window_fallback_warns_with_reason():
    """VERDICT r5 weak 6: degrading fit(window=K) to per-batch dispatch
    must WARN (once per fit) with the underlying reason instead of
    silently delivering r2-era throughput."""
    import warnings as _warnings

    from paddle_tpu import jit as jit_mod
    from paddle_tpu.io import Dataset as DS

    class Reg(DS):
        def __len__(self):
            return 12

        def __getitem__(self, i):
            x = np.full((4,), i, np.float32)
            return x, x[:1]

    paddle.seed(3)
    net = nn.Linear(4, 1)
    m = paddle.Model(net)
    m.prepare(paddle.optimizer.SGD(learning_rate=0.01,
                                   parameters=net.parameters()),
              paddle.nn.loss.MSELoss())

    class Boom(RuntimeError):
        pass

    orig = jit_mod.WindowRunner
    class Failing:
        def __init__(self, *a, **k):
            raise Boom("per_step tensor not captured")

    jit_mod.WindowRunner = Failing
    try:
        with _warnings.catch_warnings(record=True) as rec:
            _warnings.simplefilter("always")
            m.fit(Reg(), epochs=2, batch_size=4, shuffle=False,
                  verbose=0, window=3)
    finally:
        jit_mod.WindowRunner = orig
    hits = [w for w in rec if issubclass(w.category, RuntimeWarning)
            and "falling back to per-batch" in str(w.message)]
    assert len(hits) == 1, [str(w.message) for w in rec]  # once per fit
    assert "per_step tensor not captured" in str(hits[0].message)
    # training still completed on the per-batch path
    assert not m.stop_training or True

"""text / audio module tests (reference patterns:
``test/legacy_test/test_viterbi_decode_op.py``, ``test_gather_tree_op.py``,
``test/legacy_test/test_audio_functions.py``)."""
import numpy as np
import pytest

import paddle_tpu as paddle

R = np.random.default_rng(5)


def test_gather_tree():
    # example from the reference gather_tree docs
    ids = np.array([[[2, 2], [6, 1]], [[3, 9], [6, 1]], [[0, 1], [9, 0]]],
                   "int64")
    parents = np.array([[[0, 0], [1, 1]], [[1, 0], [1, 0]],
                        [[0, 0], [0, 1]]], "int64")
    out = paddle.text.gather_tree(paddle.to_tensor(ids),
                                  paddle.to_tensor(parents))
    want = np.array([[[2, 2], [1, 6]], [[3, 3], [6, 1]], [[0, 1], [9, 0]]],
                    "int64")
    np.testing.assert_array_equal(np.asarray(out._read()), want)


def _brute_viterbi(emis, trans, bos, eos):
    t, n = emis.shape
    import itertools
    best, best_s = None, -np.inf
    for path in itertools.product(range(n), repeat=t):
        s = bos[path[0]] + emis[0, path[0]]
        for i in range(1, t):
            s += trans[path[i - 1], path[i]] + emis[i, path[i]]
        s += eos[path[-1]]
        if s > best_s:
            best, best_s = path, s
    return best_s, list(best)


def test_viterbi_decode_matches_bruteforce():
    # reference convention: transition [n, n]; LAST row = start tag,
    # SECOND-TO-LAST column = stop tag (text/viterbi_decode.py:37)
    n, t = 4, 4
    emis = R.normal(size=(2, t, n)).astype("float32")
    full = R.normal(size=(n, n)).astype("float32")
    scores, paths = paddle.text.viterbi_decode(
        paddle.to_tensor(emis), paddle.to_tensor(full))
    bos = full[n - 1, :]
    eos = full[:, n - 2]
    for b in range(2):
        ws, wp = _brute_viterbi(emis[b], full, bos, eos)
        np.testing.assert_allclose(float(np.asarray(scores._read())[b]),
                                   ws, atol=1e-4)
        assert list(np.asarray(paths._read())[b]) == wp


def test_text_datasets():
    ds = paddle.text.Imdb(mode="train", n=32, seq_len=16)
    toks, label = ds[0]
    assert toks.shape == (16,) and label.shape == (1,)
    lm = paddle.text.Imikolov(n=8, seq_len=16)
    x, y = lm[0]
    np.testing.assert_array_equal(x[1:], y[:-1])


def test_mel_and_window_functions():
    import scipy.signal
    af = paddle.audio.functional
    w = np.asarray(af.get_window("hann", 64)._read())
    np.testing.assert_allclose(
        w, scipy.signal.get_window("hann", 64, fftbins=True), atol=1e-6)
    # librosa-convention slaney mel round trip
    freqs = np.array([0.0, 500.0, 1000.0, 4000.0])
    np.testing.assert_allclose(af.mel_to_hz(af.hz_to_mel(freqs)), freqs,
                               rtol=1e-6)
    assert abs(af.hz_to_mel(1000.0, htk=True) - 1000.0) < 1.0
    fb = np.asarray(af.compute_fbank_matrix(16000, 512, 40)._read())
    assert fb.shape == (40, 257) and (fb >= 0).all() and fb.sum() > 0


def test_audio_feature_layers():
    sr = 16000
    tone = np.sin(2 * np.pi * 440 *
                  np.arange(sr // 4) / sr).astype("float32")[None]
    spec = paddle.audio.Spectrogram(n_fft=512)(paddle.to_tensor(tone))
    assert tuple(spec.shape)[1] == 257
    # peak bin at 440 Hz
    peak = int(np.asarray(spec._read())[0].mean(-1).argmax())
    assert abs(peak - round(440 * 512 / sr)) <= 1
    mel = paddle.audio.MelSpectrogram(sr=sr, n_fft=512, n_mels=40)(
        paddle.to_tensor(tone))
    assert tuple(mel.shape)[1] == 40
    logmel = paddle.audio.LogMelSpectrogram(sr=sr, n_fft=512, n_mels=40)(
        paddle.to_tensor(tone))
    assert np.isfinite(np.asarray(logmel._read())).all()
    mfcc = paddle.audio.MFCC(sr=sr, n_mfcc=13, n_fft=512, n_mels=40)(
        paddle.to_tensor(tone))
    assert tuple(mfcc.shape)[1] == 13

"""text / audio module tests (reference patterns:
``test/legacy_test/test_viterbi_decode_op.py``, ``test_gather_tree_op.py``,
``test/legacy_test/test_audio_functions.py``)."""
import numpy as np
import pytest

import paddle_tpu as paddle

R = np.random.default_rng(5)


def test_gather_tree():
    # example from the reference gather_tree docs
    ids = np.array([[[2, 2], [6, 1]], [[3, 9], [6, 1]], [[0, 1], [9, 0]]],
                   "int64")
    parents = np.array([[[0, 0], [1, 1]], [[1, 0], [1, 0]],
                        [[0, 0], [0, 1]]], "int64")
    out = paddle.text.gather_tree(paddle.to_tensor(ids),
                                  paddle.to_tensor(parents))
    want = np.array([[[2, 2], [1, 6]], [[3, 3], [6, 1]], [[0, 1], [9, 0]]],
                    "int64")
    np.testing.assert_array_equal(np.asarray(out._read()), want)


def _brute_viterbi(emis, trans, bos, eos):
    t, n = emis.shape
    import itertools
    best, best_s = None, -np.inf
    for path in itertools.product(range(n), repeat=t):
        s = bos[path[0]] + emis[0, path[0]]
        for i in range(1, t):
            s += trans[path[i - 1], path[i]] + emis[i, path[i]]
        s += eos[path[-1]]
        if s > best_s:
            best, best_s = path, s
    return best_s, list(best)


def test_viterbi_decode_matches_bruteforce():
    # reference convention: transition [n, n]; LAST row = start tag,
    # SECOND-TO-LAST column = stop tag (text/viterbi_decode.py:37)
    n, t = 4, 4
    emis = R.normal(size=(2, t, n)).astype("float32")
    full = R.normal(size=(n, n)).astype("float32")
    scores, paths = paddle.text.viterbi_decode(
        paddle.to_tensor(emis), paddle.to_tensor(full))
    bos = full[n - 1, :]
    eos = full[:, n - 2]
    for b in range(2):
        ws, wp = _brute_viterbi(emis[b], full, bos, eos)
        np.testing.assert_allclose(float(np.asarray(scores._read())[b]),
                                   ws, atol=1e-4)
        assert list(np.asarray(paths._read())[b]) == wp


def test_text_datasets():
    ds = paddle.text.Imdb(mode="train", n=32, seq_len=16)
    toks, label = ds[0]
    assert toks.shape == (16,) and label.shape == (1,)
    lm = paddle.text.Imikolov(n=8, seq_len=16)
    x, y = lm[0]
    np.testing.assert_array_equal(x[1:], y[:-1])


def test_mel_and_window_functions():
    import scipy.signal
    af = paddle.audio.functional
    w = np.asarray(af.get_window("hann", 64)._read())
    np.testing.assert_allclose(
        w, scipy.signal.get_window("hann", 64, fftbins=True), atol=1e-6)
    # librosa-convention slaney mel round trip
    freqs = np.array([0.0, 500.0, 1000.0, 4000.0])
    np.testing.assert_allclose(af.mel_to_hz(af.hz_to_mel(freqs)), freqs,
                               rtol=1e-6)
    assert abs(af.hz_to_mel(1000.0, htk=True) - 1000.0) < 1.0
    fb = np.asarray(af.compute_fbank_matrix(16000, 512, 40)._read())
    assert fb.shape == (40, 257) and (fb >= 0).all() and fb.sum() > 0


def test_audio_feature_layers():
    sr = 16000
    tone = np.sin(2 * np.pi * 440 *
                  np.arange(sr // 4) / sr).astype("float32")[None]
    spec = paddle.audio.Spectrogram(n_fft=512)(paddle.to_tensor(tone))
    assert tuple(spec.shape)[1] == 257
    # peak bin at 440 Hz
    peak = int(np.asarray(spec._read())[0].mean(-1).argmax())
    assert abs(peak - round(440 * 512 / sr)) <= 1
    mel = paddle.audio.MelSpectrogram(sr=sr, n_fft=512, n_mels=40)(
        paddle.to_tensor(tone))
    assert tuple(mel.shape)[1] == 40
    logmel = paddle.audio.LogMelSpectrogram(sr=sr, n_fft=512, n_mels=40)(
        paddle.to_tensor(tone))
    assert np.isfinite(np.asarray(logmel._read())).all()
    mfcc = paddle.audio.MFCC(sr=sr, n_mfcc=13, n_fft=512, n_mels=40)(
        paddle.to_tensor(tone))
    assert tuple(mfcc.shape)[1] == 13


def test_text_dataset_breadth():
    """Round-3: UCIHousing/Conll05st/Movielens/WMT14/WMT16 structural
    parity (reference item layouts)."""
    from paddle_tpu import text

    h = text.UCIHousing()
    x, y = h[0]
    assert x.shape == (13,) and y.shape == (1,)
    assert len(text.UCIHousing(mode="test")) < len(h)

    c = text.Conll05st()
    item = c[0]
    assert len(item) == 9            # word, 5 ctx, pred, mark, label
    assert all(len(f) == len(item[0]) for f in item)

    m = text.Movielens()
    u, g, a, j, mv, title, rating = m[0]
    assert title.shape == (8,) and rating.shape == (1,)

    for cls in (text.WMT14, text.WMT16):
        src, trg, trg_next = cls()[0]
        assert len(trg) == len(trg_next)
        np.testing.assert_array_equal(trg[1:], trg_next[:-1])


def _write_wav(path, sr=16000, n=1600, freq=440.0):
    import wave
    t = np.arange(n) / sr
    data = (np.sin(2 * np.pi * freq * t) * 0.5 * 32767).astype(np.int16)
    with wave.open(str(path), "wb") as w:
        w.setnchannels(1)
        w.setsampwidth(2)
        w.setframerate(sr)
        w.writeframes(data.tobytes())


def test_audio_datasets(tmp_path):
    """TESS/ESC50 over local wav trees: filename-encoded labels, fold
    splits, raw + feature item types."""
    from paddle_tpu.audio.datasets import ESC50, TESS

    tess_dir = tmp_path / "tess"
    tess_dir.mkdir()
    for i, emo in enumerate(("angry", "happy", "sad", "neutral",
                             "fear", "disgust", "ps", "angry")):
        _write_wav(tess_dir / f"OAF_word{i}_{emo}.wav")
    ds = TESS(archive_path=str(tess_dir), mode="train", n_folds=4,
              split=1)
    ds_eval = TESS(archive_path=str(tess_dir), mode="dev", n_folds=4,
                   split=1)
    assert len(ds) + len(ds_eval) == 8
    wav, label = ds[0]
    assert wav.ndim == 1 and wav.dtype == np.float32
    assert 0 <= int(label) < 7

    esc_dir = tmp_path / "esc"
    esc_dir.mkdir()
    for fold in (1, 2):
        for tgt in (0, 7):
            _write_wav(esc_dir / f"{fold}-1000{tgt}-A-{tgt}.wav")
    tr = ESC50(archive_path=str(esc_dir), mode="train", split=1)
    ev = ESC50(archive_path=str(esc_dir), mode="dev", split=1)
    assert len(tr) == 2 and len(ev) == 2
    _, lab = tr[0]
    assert int(lab) in (0, 7)
    # feature route: mfcc item is 2-D [n_mfcc, frames]
    feat_ds = ESC50(archive_path=str(esc_dir), mode="train", split=1,
                    feat_type="mfcc", n_mfcc=13)
    f, _ = feat_ds[0]
    assert f.ndim == 2 and f.shape[0] == 13

"""Distributed checkpoint (SURVEY D23): per-shard files + manifest,
cross-topology reshard on load. Reference pattern:
python/paddle/distributed/checkpoint/{save,load}_state_dict.py."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist


def _mesh(shape, names):
    return dist.ProcessMesh(
        np.arange(int(np.prod(shape))).reshape(shape), list(names))


def test_sharded_save_layout(tmp_path):
    mesh = _mesh((2, 4), ["dp", "mp"])
    w = paddle.to_tensor(np.arange(64, dtype="float32").reshape(8, 8))
    w = dist.shard_tensor(w, mesh, [dist.Shard(0), dist.Shard(1)])
    b = paddle.to_tensor(np.arange(8, dtype="float32"))  # replicated/local
    path = str(tmp_path / "ckpt")
    dist.save_state_dict({"w": w, "b": b}, path)

    metas, datas = dist.checkpoint.api.get_checkpoint_files(path)
    assert metas == ["metadata"] and len(datas) == 1  # single process
    import pickle
    meta = pickle.load(open(f"{path}/metadata", "rb"))
    # w is split 2x4 -> 8 unique shards of (4, 2); b one block
    assert len(meta.state_dict_metadata["w"]) == 8
    assert meta.state_dict_metadata["w"][0].local_shape == (4, 2)
    assert meta.global_shapes["w"] == (8, 8)
    assert len(meta.state_dict_metadata["b"]) == 1


def test_replica_dedup(tmp_path):
    mesh = _mesh((2, 4), ["dp", "mp"])
    w = paddle.to_tensor(np.arange(32, dtype="float32").reshape(4, 8))
    # sharded over mp only -> 4 unique shards, each replicated twice on dp
    w = dist.shard_tensor(w, mesh, [dist.Replicate(), dist.Shard(1)])
    path = str(tmp_path / "ckpt")
    dist.save_state_dict({"w": w}, path)
    import pickle
    meta = pickle.load(open(f"{path}/metadata", "rb"))
    assert len(meta.state_dict_metadata["w"]) == 4  # replicas deduped


@pytest.mark.parametrize("src,dst", [
    ([0, 1], [1, 0]),        # transpose the sharded dims
    ([0, 1], [None, None]),  # sharded -> replicated
    ([None, None], [0, 1]),  # replicated -> sharded
])
def test_cross_topology_reshard(tmp_path, src, dst):
    def plc(dims):
        return [dist.Shard(d) if d is not None else dist.Replicate()
                for d in dims]

    ref = np.random.default_rng(0).normal(size=(8, 16)).astype("float32")
    mesh_a = _mesh((2, 4), ["x", "y"])
    w = dist.shard_tensor(paddle.to_tensor(ref), mesh_a, plc(src))
    path = str(tmp_path / "ckpt")
    dist.save_state_dict({"w": w}, path)

    # load into a DIFFERENT topology: 4x2 mesh, different placements
    mesh_b = _mesh((4, 2), ["x", "y"])
    w2 = dist.shard_tensor(
        paddle.to_tensor(np.zeros_like(ref)), mesh_b, plc(dst))
    dist.load_state_dict({"w": w2}, path)
    np.testing.assert_allclose(np.asarray(w2._read()), ref)
    # destination keeps its own sharding after the load. Key the set on
    # normalized (start, stop) tuples per dim: raw slice objects are
    # unhashable on Python < 3.12
    arr = w2._read()
    nshards = len({
        tuple(sl.indices(n)[:2] for sl, n in zip(s.index, arr.shape))
        for s in arr.addressable_shards})
    expected = int(np.prod([
        (4 if d == 0 else 2) for d in dst if d is not None])) or 1
    assert nshards == expected


def test_partial_and_missing_keys(tmp_path):
    mesh = _mesh((8,), ["dp"])
    w = dist.shard_tensor(
        paddle.to_tensor(np.arange(16, dtype="float32")), mesh,
        [dist.Shard(0)])
    path = str(tmp_path / "ckpt")
    dist.save_state_dict({"w": w, "extra": paddle.ones([3])}, path)
    # partial load: only request w
    tgt = paddle.zeros([16])
    dist.load_state_dict({"w": tgt}, path)
    np.testing.assert_allclose(tgt.numpy(), np.arange(16))
    with pytest.raises(KeyError):
        dist.load_state_dict({"nope": paddle.zeros([2])}, path)


def test_optimizer_state_roundtrip(tmp_path):
    """End-to-end: train, save sharded, resume on another topology."""
    mesh = _mesh((4, 2), ["dp", "mp"])
    paddle.seed(0)
    layer = paddle.nn.Linear(8, 8)
    layer.weight = dist.shard_tensor(layer.weight, mesh,
                                     [dist.Replicate(), dist.Shard(1)])
    opt = paddle.optimizer.Adam(parameters=layer.parameters())
    x = paddle.ones([4, 8])
    loss = layer(x).sum()
    loss.backward()
    opt.step()
    opt.clear_grad()

    sd = {f"p{i}": p for i, p in enumerate(layer.parameters())}
    path = str(tmp_path / "ckpt")
    dist.save_state_dict(sd, path)

    mesh2 = _mesh((2, 4), ["dp", "mp"])
    paddle.seed(1)
    layer2 = paddle.nn.Linear(8, 8)
    layer2.weight = dist.shard_tensor(layer2.weight, mesh2,
                                      [dist.Shard(0), dist.Replicate()])
    sd2 = {f"p{i}": p for i, p in enumerate(layer2.parameters())}
    dist.load_state_dict(sd2, path)
    for k in sd:
        np.testing.assert_allclose(np.asarray(sd2[k]._read()),
                                   np.asarray(sd[k]._read()), rtol=1e-6)

"""Semi-auto (GSPMD) API tests: shard_tensor/reshard/shard_layer/
shard_optimizer + a 2-D dp×mp MLP trained on the virtual mesh with
sharding asserted (VERDICT round-1 item 3; reference pattern
test/auto_parallel/semi_auto_parallel_simple_net_dp_mp_pp.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist


@pytest.fixture
def mesh2d():
    return dist.ProcessMesh(np.arange(8).reshape(4, 2), ["dp", "mp"])


def _device_count_of(t):
    return len(t._read().sharding.device_set)


def test_shard_tensor_basic(mesh2d):
    x = paddle.to_tensor(np.random.randn(8, 6).astype(np.float32))
    d = dist.shard_tensor(x, mesh2d, [dist.Shard(0), dist.Replicate()])
    assert d.is_dist()
    assert d.process_mesh is mesh2d
    assert d.placements[0] == dist.Shard(0)
    np.testing.assert_allclose(d.numpy(), x.numpy())
    # sharded over 4-way dp on dim 0: addressable shards are [2, 6]
    shard_shapes = {s.data.shape for s in d._read().addressable_shards}
    assert shard_shapes == {(2, 6)}


def test_shard_tensor_2d(mesh2d):
    x = paddle.to_tensor(np.random.randn(8, 6).astype(np.float32))
    d = dist.shard_tensor(x, mesh2d, [dist.Shard(0), dist.Shard(1)])
    shard_shapes = {s.data.shape for s in d._read().addressable_shards}
    assert shard_shapes == {(2, 3)}


def test_reshard(mesh2d):
    x = paddle.to_tensor(np.random.randn(8, 6).astype(np.float32))
    d = dist.shard_tensor(x, mesh2d, [dist.Shard(0), dist.Replicate()])
    r = dist.reshard(d, mesh2d, [dist.Replicate(), dist.Shard(1)])
    np.testing.assert_allclose(r.numpy(), x.numpy())
    shard_shapes = {s.data.shape for s in r._read().addressable_shards}
    assert shard_shapes == {(8, 3)}
    assert r.placements[1] == dist.Shard(1)


def test_reshard_differentiable(mesh2d):
    x = paddle.to_tensor(np.random.randn(8, 6).astype(np.float32),
                         stop_gradient=False)
    d = dist.reshard(x, mesh2d, [dist.Shard(0)])
    loss = (d * d).sum()
    loss.backward()
    np.testing.assert_allclose(x.grad.numpy(), 2 * x.numpy(), rtol=1e-5)


def test_dtensor_from_fn(mesh2d):
    d = dist.dtensor_from_fn(paddle.ones, mesh2d, [dist.Replicate()], [4, 4])
    assert d.is_dist()
    np.testing.assert_allclose(d.numpy(), np.ones((4, 4)))


def test_partial_is_metadata(mesh2d):
    x = paddle.to_tensor(np.random.randn(4, 4).astype(np.float32))
    d = dist.shard_tensor(x, mesh2d, [dist.Partial(), dist.Replicate()])
    assert d.placements[0].is_partial()
    r = dist.reshard(d, mesh2d, [dist.Replicate(), dist.Replicate()])
    np.testing.assert_allclose(r.numpy(), x.numpy())


class _MLP(paddle.nn.Layer):
    def __init__(self, din=8, dh=32, dout=4):
        super().__init__()
        self.fc1 = paddle.nn.Linear(din, dh)
        self.fc2 = paddle.nn.Linear(dh, dout)

    def forward(self, x):
        return self.fc2(paddle.nn.functional.relu(self.fc1(x)))


def _mp_shard_fn(name, sub, mesh):
    """Megatron split: fc1 column-parallel, fc2 row-parallel over mp."""
    if name.endswith("fc1"):
        dist.shard_parameter(sub.weight, mesh,
                             [dist.Replicate(), dist.Shard(1)])
        dist.shard_parameter(sub.bias, mesh,
                             [dist.Replicate(), dist.Shard(0)])
    elif name.endswith("fc2"):
        dist.shard_parameter(sub.weight, mesh,
                             [dist.Replicate(), dist.Shard(0)])


def test_shard_layer_and_train_dp_mp(mesh2d):
    """2-D dp×mp training parity vs single-device, shardings asserted."""
    paddle.seed(11)
    ref = _MLP()
    paddle.seed(11)
    net = _MLP()
    dist.shard_layer(net, mesh2d, _mp_shard_fn)

    # weight shardings took effect
    w1 = net.fc1.weight._read()
    assert {s.data.shape for s in w1.addressable_shards} == {(8, 16)}
    w2 = net.fc2.weight._read()
    assert {s.data.shape for s in w2.addressable_shards} == {(16, 4)}

    opt_ref = paddle.optimizer.Adam(learning_rate=0.01,
                                    parameters=ref.parameters())
    opt = dist.shard_optimizer(paddle.optimizer.Adam(
        learning_rate=0.01, parameters=net.parameters()))

    rng = np.random.RandomState(3)
    for step in range(3):
        xb = rng.randn(16, 8).astype(np.float32)
        yb = rng.randn(16, 4).astype(np.float32)

        x = paddle.to_tensor(xb)
        y = paddle.to_tensor(yb)
        l_ref = ((ref(x) - y) ** 2).mean()
        l_ref.backward()
        opt_ref.step()
        opt_ref.clear_grad()

        xd = dist.shard_tensor(paddle.to_tensor(xb), mesh2d, [dist.Shard(0)])
        y = paddle.to_tensor(yb)
        l = ((net(xd) - y) ** 2).mean()
        l.backward()
        opt.step()
        opt.clear_grad()

        np.testing.assert_allclose(float(l_ref), float(l), rtol=1e-4)

    # weights stayed in sync across the two runs
    np.testing.assert_allclose(net.fc1.weight.numpy(),
                               ref.fc1.weight.numpy(), rtol=1e-4)


def test_shard_optimizer_zero1(mesh2d):
    """shard_fn puts moments sharded over dp — ZeRO-1 layout."""
    net = _MLP()
    dist.shard_layer(net, mesh2d)

    def moment_shard(acc_name, param, acc):
        if param.shape[0] % 4 == 0:
            return [dist.Shard(0), dist.Replicate()]
        return None

    opt = dist.shard_optimizer(
        paddle.optimizer.Adam(learning_rate=0.01,
                              parameters=net.parameters()),
        shard_fn=moment_shard)
    x = paddle.to_tensor(np.random.randn(4, 8).astype(np.float32))
    loss = net(x).sum()
    loss.backward()
    opt.step()
    m = opt._inner._accumulators["moment1"][id(net.fc1.weight)]
    assert {s.data.shape for s in m._read().addressable_shards} == {(2, 32)}


def test_to_static_sharded_step(mesh2d):
    """A sharded train step compiles to ONE SPMD program via jit capture."""
    paddle.seed(5)
    net = _MLP()
    dist.shard_layer(net, mesh2d, _mp_shard_fn)
    opt = paddle.optimizer.SGD(learning_rate=0.05,
                               parameters=net.parameters())

    @paddle.jit.to_static
    def step(x, y):
        loss = ((net(x) - y) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    # eager twin for parity
    paddle.seed(5)
    ref = _MLP()
    opt_ref = paddle.optimizer.SGD(learning_rate=0.05,
                                   parameters=ref.parameters())

    rng = np.random.RandomState(1)
    xb = rng.randn(16, 8).astype(np.float32)
    yb = rng.randn(16, 4).astype(np.float32)
    losses, ref_losses = [], []
    for _ in range(4):
        xd = dist.shard_tensor(paddle.to_tensor(xb), mesh2d,
                               [dist.Shard(0)])
        y = paddle.to_tensor(yb)
        losses.append(float(step(xd, y)))

        x = paddle.to_tensor(xb)
        y = paddle.to_tensor(yb)
        l_ref = ((ref(x) - y) ** 2).mean()
        l_ref.backward()
        opt_ref.step()
        opt_ref.clear_grad()
        ref_losses.append(float(l_ref))

    np.testing.assert_allclose(losses, ref_losses, rtol=1e-4)
    assert losses[-1] < losses[0]  # fixed batch: SGD must make progress
    # weight sharding preserved through compiled steps
    w1 = net.fc1.weight._read()
    assert {s.data.shape for s in w1.addressable_shards} == {(8, 16)}

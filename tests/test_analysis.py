"""Graph lint (``paddle_tpu.analysis``): registry golden tests, both
front-ends, suppression (pragma / context / decorator), the mode flag
(``PDTPU_ANALYSIS=off|warn|error``), the to_static + dy2static wiring,
and the CLI."""
import textwrap
import warnings

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import analysis
from paddle_tpu.analysis import LintWarning, Severity
from paddle_tpu.core import errors


@pytest.fixture(autouse=True)
def _fresh_dedup():
    analysis.reset_reported()
    yield
    analysis.reset_reported()


@pytest.fixture
def _mode():
    """Set the analysis mode flag for one test, restoring after."""
    old = paddle.get_flags("analysis")["analysis"]

    def set_mode(m):
        paddle.set_flags({"analysis": m})

    yield set_mode
    paddle.set_flags({"analysis": old})


# ==========================================================================
# registry golden tests (satellite: parametrized over every code)
# ==========================================================================

def test_registry_catalog_shape():
    assert len(analysis.REGISTRY) >= 8
    names = set()
    for code, spec in analysis.REGISTRY.items():
        assert code == spec.code
        assert code.startswith("PDT1") or code.startswith("PDT2")
        assert (spec.frontend == "ast") == code.startswith("PDT1")
        assert spec.frontend in ("ast", "ir", "runtime")
        assert spec.doc.strip(), f"{code} has no docstring"
        assert spec.example.strip(), f"{code} has no example"
        assert spec.near_miss.strip(), f"{code} has no near-miss"
        assert spec.severity in (Severity.NOTE, Severity.WARN,
                                 Severity.ERROR)
        assert spec.name and spec.name not in names, \
            f"{code} name not unique"
        names.add(spec.name)
    # both front-ends are populated
    assert sum(s.frontend == "ast" for s in analysis.REGISTRY.values()) >= 4
    assert sum(s.frontend != "ast" for s in analysis.REGISTRY.values()) >= 4


@pytest.mark.parametrize("code", sorted(analysis.REGISTRY))
def test_registry_golden(code):
    """Every code: the example triggers it, the near-miss does not, and
    ``analysis.suppress(code)`` silences the example."""
    spec = analysis.REGISTRY[code]
    hits = analysis.exercise(spec, "example")
    assert any(d.code == code for d in hits), \
        f"{code} example did not trigger (got {[d.code for d in hits]})"
    misses = analysis.exercise(spec, "near_miss")
    assert not [d for d in misses if d.code == code], \
        f"{code} near-miss triggered: {[d.format() for d in misses]}"
    with analysis.suppress(code):
        suppressed = analysis.exercise(spec, "example")
    assert not [d for d in suppressed if d.code == code], \
        f"{code} not suppressed by analysis.suppress"


def test_register_rejects_bad_specs():
    with pytest.raises(ValueError, match="PDT"):
        analysis.register("XXX", "bad", Severity.WARN, "ast",
                          example="x", near_miss="y")
    with pytest.raises(ValueError, match="duplicate"):
        @analysis.register("PDT101", "dup", Severity.WARN, "ast",
                           example="x", near_miss="y")
        def dup(fndef, ctx):
            """Dup."""
            return []
    with pytest.raises(ValueError, match="AST"):
        analysis.register("PDT131", "wrong-range", Severity.WARN, "ir",
                          example="x", near_miss="y")


# ==========================================================================
# suppression: pragma, context, decorator
# ==========================================================================

_HOSTILE_SRC = """
import paddle_tpu as paddle

@paddle.jit.to_static
def step(x):
    return x.numpy()
"""


def test_pragma_line_suppression():
    src = _HOSTILE_SRC.replace("x.numpy()",
                               "x.numpy()  # pdtpu: noqa[PDT101]")
    assert not analysis.analyze_source(src)
    src_all = _HOSTILE_SRC.replace("x.numpy()", "x.numpy()  # pdtpu: noqa")
    assert not analysis.analyze_source(src_all)
    # unrelated code listed -> finding stays
    src_other = _HOSTILE_SRC.replace("x.numpy()",
                                     "x.numpy()  # pdtpu: noqa[PDT106]")
    assert [d.code for d in analysis.analyze_source(src_other)] == ["PDT101"]


def test_pragma_on_def_line_covers_function():
    src = _HOSTILE_SRC.replace("def step(x):",
                               "def step(x):  # pdtpu: noqa[PDT101]")
    assert not analysis.analyze_source(src)


def test_pragma_on_decorator_line_covers_function():
    """Regression (ISSUE 16 satellite): on a decorated def the pragma
    anchors to the full def header span — decorator lines included —
    not just the ``def`` line."""
    src = _HOSTILE_SRC.replace(
        "@paddle.jit.to_static",
        "@paddle.jit.to_static  # pdtpu: noqa[PDT101]")
    assert not analysis.analyze_source(src)
    # a pragma for an unrelated code on the decorator changes nothing
    other = _HOSTILE_SRC.replace(
        "@paddle.jit.to_static",
        "@paddle.jit.to_static  # pdtpu: noqa[PDT106]")
    assert [d.code for d in analysis.analyze_source(other)] == ["PDT101"]


_MULTILINE_SRC = """
import paddle_tpu as paddle

@paddle.jit.to_static
def step(x):
    y = (x
         .numpy())
    return y
"""


def test_pragma_anchors_to_multiline_statement_span():
    """Regression (ISSUE 16 satellite): suppression covers the full
    line span of a multiline statement, wherever the pragma sits in
    it — not only the line the AST node starts on."""
    assert [d.code for d in analysis.analyze_source(_MULTILINE_SRC)] \
        == ["PDT101"]
    on_last = _MULTILINE_SRC.replace(".numpy())",
                                     ".numpy())  # pdtpu: noqa[PDT101]")
    assert not analysis.analyze_source(on_last)
    on_first = _MULTILINE_SRC.replace("y = (x",
                                      "y = (x  # pdtpu: noqa[PDT101]")
    assert not analysis.analyze_source(on_first)


def test_pragma_outside_statement_span_does_not_suppress():
    after = _MULTILINE_SRC.replace(
        "    return y", "    # pdtpu: noqa[PDT101]\n    return y")
    assert [d.code for d in analysis.analyze_source(after)] == ["PDT101"]


def test_suppress_context_manager():
    assert analysis.analyze_source(_HOSTILE_SRC)
    with analysis.suppress("PDT101"):
        assert not analysis.analyze_source(_HOSTILE_SRC)
    with analysis.suppress():  # bare: all codes
        assert not analysis.analyze_source(_HOSTILE_SRC)
    assert analysis.analyze_source(_HOSTILE_SRC)  # restored on exit


def test_suppress_instance_reentry_does_not_leak():
    """Nested re-entry of ONE suppress instance must unwind cleanly —
    a leaked frame would silence its codes process-wide forever."""
    s = analysis.suppress("PDT101")
    with s:
        with s:
            assert not analysis.analyze_source(_HOSTILE_SRC)
        assert not analysis.analyze_source(_HOSTILE_SRC)  # outer holds
    assert analysis.analyze_source(_HOSTILE_SRC)  # fully restored


def test_suppress_decorator_tags_function():
    @analysis.suppress("PDT101")
    def step(x):
        return x.numpy()

    assert step.__pdtpu_suppress__ == frozenset({"PDT101"})
    assert not [d for d in analysis.check_function(step)
                if d.code == "PDT101"]

    def step2(x):
        return x.numpy()

    assert [d.code for d in analysis.check_function(step2)] == ["PDT101"]


def test_nested_functions_lint_as_own_scope():
    """Inline helpers inside a jit function are traced too, so they are
    linted — but as their own scope, with their own suppression."""
    src = """
import paddle_tpu as paddle
from paddle_tpu import analysis

@paddle.jit.to_static
def step(x):
    def helper(v):
        return v.numpy()
    return helper(x)
"""
    diags = analysis.analyze_source(src)
    assert [d.code for d in diags] == ["PDT101"]
    # suppression on the NESTED def governs the nested finding
    tagged = src.replace("def helper(v):",
                         "@analysis.suppress(\"PDT101\")\n    "
                         "def helper(v):")
    assert not analysis.analyze_source(tagged)
    # a def-line pragma on the helper works too
    pragma = src.replace("def helper(v):",
                         "def helper(v):  # pdtpu: noqa[PDT101]")
    assert not analysis.analyze_source(pragma)


def test_plain_scalar_casts_not_flagged():
    """float()/int() on plain names are ordinary Python conversions,
    not host syncs — only the tensor-shaped float(x.sum()) pattern
    warns."""
    src = """
import paddle_tpu as paddle

@paddle.jit.to_static
def step(x, lr):
    scale = float(lr)
    n = int(3.5)
    return x * scale * n
"""
    assert not analysis.analyze_source(src)
    hostile = src.replace("float(lr)", "float(x.sum())")
    assert [d.code for d in analysis.analyze_source(hostile)] == ["PDT101"]


def test_suppress_decorator_visible_to_source_lint():
    """The CLI (source-only) honors @analysis.suppress syntactically,
    matching the runtime tag the decorator sets."""
    src = """
import paddle_tpu as paddle
from paddle_tpu import analysis

@paddle.jit.to_static
@analysis.suppress("PDT101")
def step(x):
    return x.numpy()
"""
    assert not [d for d in analysis.analyze_source(src)
                if d.code == "PDT101"]
    bare = src.replace('analysis.suppress("PDT101")', "analysis.suppress()")
    assert not analysis.analyze_source(bare)
    other = src.replace('"PDT101"', '"PDT106"')
    assert [d.code for d in analysis.analyze_source(other)] == ["PDT101"]


def test_check_function_reports_real_file_and_line():
    def step(x):
        return x.numpy()

    diags = analysis.check_function(step)
    assert len(diags) == 1
    assert diags[0].file.endswith("test_analysis.py")
    # the finding points at the `return x.numpy()` line of THIS file
    import inspect
    lines, start = inspect.getsourcelines(step)
    assert diags[0].line == start + 1


# ==========================================================================
# mode flag: off | warn | error  (to_static wiring)
# ==========================================================================

def _entropy_fn():
    # triggers PDT106 but still captures fine (constant gets baked)
    import random

    @paddle.jit.to_static
    def step(x):
        return x * random.random()
    return step


def test_mode_off_is_silent(_mode):
    _mode("off")
    step = _entropy_fn()
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        step(paddle.to_tensor(np.ones(2, np.float32)))
    assert not [x for x in w if isinstance(x.message, LintWarning)]


def test_mode_warn_emits_lint_warning(_mode):
    _mode("warn")
    step = _entropy_fn()
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        step(paddle.to_tensor(np.ones(2, np.float32)))
    lint = [x for x in w if isinstance(x.message, LintWarning)]
    assert any("PDT106" in str(x.message) for x in lint)
    # dedup: the same site reports once per session
    with warnings.catch_warnings(record=True) as w2:
        warnings.simplefilter("always")
        analysis.lint_callable(step.fn)
    assert not [x for x in w2 if "PDT106" in str(x.message)]


def test_mode_error_raises(_mode):
    _mode("error")
    step = _entropy_fn()
    t = paddle.to_tensor(np.ones(2, np.float32))
    with pytest.raises(errors.StaticAnalysisError, match="PDT106"):
        step(t)
    # the gate holds across calls (not a one-shot raise) ...
    with pytest.raises(errors.StaticAnalysisError, match="PDT106"):
        step(t)
    # ... and the blocked calls did not burn the conversion attempt:
    # once suppressed, the function still captures and runs
    with analysis.suppress("PDT106"):
        out = step(t)
    assert out.shape == [2]


def test_mode_error_respects_suppression(_mode):
    _mode("error")

    @analysis.suppress("PDT106")
    def raw(x):
        import random
        return x * random.random()

    step = paddle.jit.to_static(raw)
    out = step(paddle.to_tensor(np.ones(2, np.float32)))
    assert out.shape == [2]


def test_warn_mode_dedup_does_not_disarm_error_gate(_mode):
    """A site already surfaced as a warning must still raise once the
    user escalates to error mode."""
    _mode("warn")

    def fn(x):
        return x.numpy()

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        analysis.lint_callable(fn)
    assert any("PDT101" in str(x.message) for x in w)
    _mode("error")
    with pytest.raises(errors.StaticAnalysisError, match="PDT101"):
        analysis.lint_callable(fn)


def test_mode_error_gates_dy2static_decline(_mode):
    """A conversion-decline diagnostic (foreign decorator -> PDT107)
    must surface through _converted's exception handling, repeatedly,
    without burning the conversion attempt."""
    _mode("error")

    def deco(f):
        return f

    @deco
    def fn(x):
        if x.mean() > 0:
            return x * 2
        return x

    step = paddle.jit.to_static(fn)
    t = paddle.to_tensor(np.ones(2, np.float32))
    with pytest.raises(errors.StaticAnalysisError, match="PDT107"):
        step(t)
    with pytest.raises(errors.StaticAnalysisError, match="PDT107"):
        step(t)  # gate holds across calls
    with analysis.suppress("PDT107"), warnings.catch_warnings():
        warnings.simplefilter("ignore")  # eager fallback chatter
        out = step(t)
    np.testing.assert_allclose(out.numpy(), [2.0, 2.0])


# ==========================================================================
# dy2static graph-break decline sites emit PDT1xx (satellite)
# ==========================================================================

def test_dy2static_decline_emits_pdt105():
    @paddle.jit.to_static
    def fn(x):
        if x.sum() > 0:  # escape inside try blocks conversion of the if
            try:
                return x * 2
            finally:
                pass
        return x

    with analysis.collect() as diags:
        fn(paddle.to_tensor(np.ones(2, np.float32)))
    hits = [d for d in diags if d.code == "PDT105"]
    assert hits, f"no PDT105 in {[d.format() for d in diags]}"
    assert hits[0].file.endswith("test_analysis.py")
    import inspect
    lines, start = inspect.getsourcelines(fn.fn)
    assert start < hits[0].line < start + len(lines)


def test_dy2static_nonlocal_decline_emits_pdt107():
    k = [0]

    def outer():
        n = 0

        def fn(x):
            nonlocal n
            n += 1
            return x * 2
        return fn

    step = paddle.jit.to_static(outer())
    with analysis.collect() as diags:
        step(paddle.to_tensor(np.ones(2, np.float32)))
    assert any(d.code == "PDT107" for d in diags), \
        [d.format() for d in diags]
    assert k == [0]  # sanity: closure untouched


def test_suppress_decorator_composes_with_to_static():
    """@analysis.suppress must not block dy2static conversion (it tags,
    it does not wrap): tensor control flow still compiles."""
    @paddle.jit.to_static
    @analysis.suppress("PDT106")
    def fn(x):
        if x.mean() > 0:
            y = x * 2.0
        else:
            y = x - 1.0
        return y

    t = paddle.to_tensor(np.asarray([1.0, 2.0], np.float32))
    np.testing.assert_allclose(fn(t).numpy(), [2.0, 4.0])
    np.testing.assert_allclose(
        fn(paddle.to_tensor(np.asarray([-1.0, -2.0], np.float32))).numpy(),
        [-2.0, -3.0])
    sf = fn if hasattr(fn, "_fallback_keys") else fn.__wrapped__
    assert not sf._fallback_keys, "suppress decorator broke conversion"
    assert len(sf._cache) == 1


# ==========================================================================
# IR front-end wiring: captured executables carry a jaxpr + lint hookup
# ==========================================================================

def test_capture_runs_ir_lint_then_releases_jaxpr():
    w = paddle.to_tensor(np.ones(4, np.float32))
    n = paddle.to_tensor(3)  # weak-typed python-int scalar state

    @paddle.jit.to_static
    def step2(x):
        return x * 2 + w.sum() + n

    t = paddle.to_tensor(np.ones(4, np.float32))
    with analysis.collect() as diags:
        out = step2(t)
    np.testing.assert_allclose(out.numpy(), np.ones(4) * 2 + 4 + 3)
    # the weak-typed capture input surfaced through the capture-time
    # IR lint (PDT205 is note severity: visible to collect, not warned)
    assert any(d.code == "PDT205" for d in diags), \
        [d.format() for d in diags]
    exe = step2.concrete_program(t)
    assert exe is not None
    assert exe.jaxpr is None  # released after the capture lint (memory)
    assert exe.n_explicit_args == 1
    assert analysis.check_executable(exe) == []  # released -> no-op


def test_suppress_tag_covers_ir_findings():
    n = paddle.to_tensor(3)

    @paddle.jit.to_static
    @analysis.suppress("PDT205")
    def step(x):
        return x + n

    with analysis.collect() as diags:
        step(paddle.to_tensor(np.ones(4, np.float32)))
    assert not [d for d in diags if d.code == "PDT205"], \
        [d.format() for d in diags]


def test_report_runtime_each_occurrence_and_never_raises(_mode):
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        analysis.report_runtime("PDT206", "loop A truncated")
        analysis.report_runtime("PDT206", "loop B truncated")
    lint = [x for x in w if isinstance(x.message, LintWarning)]
    assert len(lint) == 2  # runtime events are never deduped
    _mode("error")
    with warnings.catch_warnings(record=True) as w2:
        warnings.simplefilter("always")
        analysis.report_runtime("PDT206", "truncated mid-step")
    # fires mid-execution (jax.debug.callback): degrade to a warning
    # rather than aborting the compiled step with a corrupt result
    assert any(isinstance(x.message, LintWarning) for x in w2)
    # and even with the lint OFF, a warn-severity runtime event (wrong
    # numerics) is not silenced
    _mode("off")
    with warnings.catch_warnings(record=True) as w3:
        warnings.simplefilter("always")
        analysis.report_runtime("PDT206", "truncated with lint off")
    assert any(isinstance(x.message, LintWarning) for x in w3)


def test_check_traced_flags_dead_code_and_weak_types():
    import jax.numpy as jnp

    def f(x):
        unused = jnp.sin(x) * jnp.cos(x)
        return x * 2

    codes = {d.code for d in analysis.check_traced(
        f, jnp.ones((4,), jnp.float32))}
    assert "PDT204" in codes
    codes2 = {d.code for d in analysis.check_traced(lambda x: x * 2.0, 3.0)}
    assert "PDT205" in codes2


# ==========================================================================
# hapi wiring
# ==========================================================================

def test_hapi_prepare_lints_network(_mode):
    from paddle_tpu import nn

    class Hostile(nn.Layer):
        def forward(self, x):  # linted with jit=True by prepare
            import random
            return x * random.random()

    _mode("error")
    m = paddle.Model(Hostile())
    with pytest.raises(errors.StaticAnalysisError, match="PDT106"):
        m.prepare(loss=nn.MSELoss())

    _mode("off")
    m2 = paddle.Model(Hostile())
    m2.prepare(loss=nn.MSELoss())  # off: same network sails through


# ==========================================================================
# CLI
# ==========================================================================

def _write(tmp_path, name, src):
    p = tmp_path / name
    p.write_text(textwrap.dedent(src))
    return p


def test_cli_finds_and_gates(tmp_path, capsys):
    from paddle_tpu.analysis.__main__ import main
    _write(tmp_path, "bad.py", """
        import paddle_tpu as paddle

        @paddle.jit.to_static
        def step(x):
            return x.numpy()
        """)
    _write(tmp_path, "clean.py", """
        def helper(x):
            return x.numpy()  # not jit: fine
        """)
    rc = main([str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 0  # warn severity does not gate by default
    assert "PDT101" in out and "bad.py" in out and "clean.py" not in out
    assert "(0 error, 1 warn, 0 note)" in out

    rc = main([str(tmp_path), "--strict"])
    capsys.readouterr()
    assert rc == 1  # --strict gates on warn

    rc = main([str(tmp_path), "--select", "PDT106", "--strict"])
    capsys.readouterr()
    assert rc == 0  # filtered out


def test_cli_assume_jit(tmp_path, capsys):
    from paddle_tpu.analysis.__main__ import main
    _write(tmp_path, "plain.py", """
        def helper(x):
            return x.numpy()
        """)
    rc = main([str(tmp_path)])
    assert "PDT101" not in capsys.readouterr().out and rc == 0
    rc = main([str(tmp_path), "--assume-jit"])
    assert "PDT101" in capsys.readouterr().out and rc == 0


def test_cli_list_codes(capsys):
    from paddle_tpu.analysis.__main__ import main
    assert main(["--list-codes"]) == 0
    out = capsys.readouterr().out
    for code in analysis.REGISTRY:
        assert code in out


def test_cli_list_codes_markdown(capsys):
    from paddle_tpu.analysis.__main__ import main
    assert main(["--list-codes", "--format", "markdown"]) == 0
    out = capsys.readouterr().out
    assert out.startswith("| code |")
    for code in analysis.REGISTRY:
        assert f"| {code} |" in out


def test_cli_format_json(tmp_path, capsys):
    """Satellite: machine-readable findings with the stable exit codes
    (0 clean / 1 gating findings / 2 usage error)."""
    import json as _json

    from paddle_tpu.analysis.__main__ import main
    _write(tmp_path, "bad.py", """
        import paddle_tpu as paddle

        @paddle.jit.to_static
        def step(x):
            return x.numpy()
        """)
    rc = main([str(tmp_path), "--format", "json"])
    doc = _json.loads(capsys.readouterr().out)
    assert rc == 0  # warn severity does not gate by default
    assert doc["summary"] == {"files": 1, "error": 0, "warn": 1,
                              "note": 0, "gating": 0}
    (f,) = doc["findings"]
    assert f["code"] == "PDT101" and f["path"].endswith("bad.py")
    assert f["severity"] == "warn" and f["line"] > 0 and f["col"] >= 0
    assert "numpy" in f["message"]

    rc = main([str(tmp_path), "--format", "json", "--strict"])
    doc = _json.loads(capsys.readouterr().out)
    assert rc == 1 and doc["summary"]["gating"] == 1


def test_cli_programs_entry_and_usage_exit(capsys):
    from paddle_tpu.analysis.__main__ import main
    # a harmless entry point: imports, runs, audits clean
    assert main(["--programs", "paddle_tpu.analysis:mode"]) == 0
    capsys.readouterr()
    # import failures are usage errors (exit 2), not findings
    assert main(["--programs", "no_such_module:thing"]) == 2
    assert "cannot load" in capsys.readouterr().err

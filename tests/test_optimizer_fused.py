"""Fused multi-tensor optimizer path: parity, views, capture, comms.

The fused path (optimizer/flat.py + ops/pallas/fused_optimizer.py) must
be BIT-EXACT against the per-param path on CPU for every supported
optimizer x dtype x clip x decay combination. Test grads are
integer-valued so the single-reduction global-norm clip sums exactly in
any association order — elementwise update arithmetic is order-free, so
everything downstream stays bitwise comparable.
"""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
from paddle_tpu import optimizer as opt
from paddle_tpu.core import state as st
from paddle_tpu.nn import ClipGradByGlobalNorm

SHAPES = [(6, 3), (17,), (2, 2, 2)]


@pytest.fixture(autouse=True)
def _fused_on():
    yield
    st.set_flags({"fused_opt": True})


def _params(dtype="float32", seed=0):
    rng = np.random.default_rng(seed)
    ps = []
    for s in SHAPES:
        v = rng.integers(-4, 5, s).astype("float32")
        p = pt.Parameter(v)
        if dtype != "float32":
            p._write(p._read().astype(dtype))
        ps.append(p)
    return ps


def _grads(step, seed=1):
    rng = np.random.default_rng(seed + step)
    return [rng.integers(-3, 4, s).astype("float32") for s in SHAPES]


def _factories():
    return {
        "sgd": lambda ps, **kw: opt.SGD(0.1, parameters=ps, **kw),
        "momentum": lambda ps, **kw: opt.Momentum(
            0.1, 0.9, parameters=ps, use_nesterov=True, **kw),
        "adam": lambda ps, **kw: opt.Adam(0.05, parameters=ps, **kw),
        "adamw": lambda ps, **kw: opt.AdamW(
            0.05, parameters=ps, weight_decay=0.1, **kw),
    }


def _run(name, fused, dtype, clip, decay, steps=3):
    st.set_flags({"fused_opt": fused})
    ps = _params(dtype)
    kw = {}
    if clip:
        kw["grad_clip"] = ClipGradByGlobalNorm(2.0)
    if dtype != "float32":
        kw["multi_precision"] = True
    if decay and name != "adamw":  # adamw decay is decoupled (built in)
        kw["weight_decay"] = decay
    o = _factories()[name](ps, **kw)
    for i in range(steps):
        for p, g in zip(ps, _grads(i)):
            gv = g if dtype == "float32" else g.astype(dtype)
            p.grad = pt.to_tensor(gv)
        o.step()
        o.clear_grad()
    out = {f"p{i}": np.asarray(p._read()) for i, p in enumerate(ps)}
    for i, p in enumerate(ps):
        p.name = f"w{i}"
    # state_dict normalizes fused vs per-param layout (beta pows are
    # per-bucket scalars on the fused path, full arrays per-param —
    # same VALUE either way)
    for key, t in o.state_dict().items():
        if key in ("@step", "LR_Scheduler"):
            continue
        a = np.asarray(t._read())
        out[key] = a.ravel()[:1] if "_pow" in key else a
    return out, o


@pytest.mark.parametrize("name", ["sgd", "momentum", "adam", "adamw"])
@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
@pytest.mark.parametrize("clip", [False, True])
def test_fused_bitwise_parity(name, dtype, clip):
    ref, _ = _run(name, fused=False, dtype=dtype, clip=clip, decay=None)
    got, o = _run(name, fused=True, dtype=dtype, clip=clip, decay=None)
    assert o._flat, "fused path did not engage"
    assert set(got) == set(ref)
    for k in ref:
        assert np.array_equal(ref[k], got[k]), f"{k} differs"


@pytest.mark.parametrize("name,dtype,decay", [
    ("sgd", "float32", opt.L2Decay(0.5)),
    ("momentum", "float32", opt.L2Decay(0.5)),
    ("adam", "float32", opt.L2Decay(0.5)),
    ("sgd", "float32", opt.L1Decay(0.3)),
    ("adam", "bfloat16", opt.L2Decay(0.5)),
    ("adamw", "bfloat16", None),  # decoupled decay x master weights
])
def test_fused_parity_with_regularizer(name, dtype, decay):
    ref, _ = _run(name, fused=False, dtype=dtype, clip=True, decay=decay)
    got, o = _run(name, fused=True, dtype=dtype, clip=True, decay=decay)
    assert o._flat
    for k in ref:
        assert np.array_equal(ref[k], got[k]), f"{k} differs"


# ---------------------------------------------------------------- views --
def test_clear_grad_zeroes_flat_bucket_in_one_op():
    """Satellite: set_to_zero=True zeroes the flat grad bucket with ONE
    op; the per-param grad views observe the zeros lazily."""
    ps = _params()
    o = opt.Adam(0.01, parameters=ps)
    for p, g in zip(ps, _grads(0)):
        p.grad = pt.to_tensor(g)
    o.step()
    grads_before = [p.grad for p in ps]
    o.clear_grad(set_to_zero=True)
    # identity stable, bound as views, caches invalidated (lazy zeros)
    st0 = o._flat[0].grad_store
    for p, g0 in zip(ps, grads_before):
        assert p.grad is g0
        assert p.grad._flat_view is not None
        # no per-view zero materialized yet: caches still anchor the
        # pre-zero flat array, so the zeros arrive lazily on read
        assert p.grad._flat_src is not st0.storage._data
    assert not np.any(np.asarray(st0.storage._read()))
    for p in ps:
        assert not np.any(np.asarray(p.grad._read()))
    # accumulation into the zeroed views still works
    for p, g in zip(ps, _grads(1)):
        p._accumulate_grad(pt.to_tensor(g)._read())
    np.testing.assert_array_equal(np.asarray(ps[0].grad._read()),
                                  _grads(1)[0])


def test_fused_eager_dispatches_o_buckets():
    """The eager fused update dispatches O(buckets) kernels and never
    walks the per-param _update."""
    from paddle_tpu.ops.pallas import fused_optimizer as fo
    ps = _params()
    o = opt.AdamW(0.01, parameters=ps)
    calls = []
    orig_fused, orig_upd = fo.fused_update, opt.AdamW._update

    def counting(*a, **k):
        calls.append("fused")
        return orig_fused(*a, **k)

    def no_per_param(self, *a, **k):  # pragma: no cover - must not run
        calls.append("per-param")
        return orig_upd(self, *a, **k)
    fo.fused_update = counting
    opt.AdamW._update = no_per_param
    try:
        for i in range(2):
            for p, g in zip(ps, _grads(i)):
                p.grad = pt.to_tensor(g)
            o.step()
            o.clear_grad()
    finally:
        fo.fused_update = orig_fused
        opt.AdamW._update = orig_upd
    assert calls == ["fused", "fused"]  # one kernel per bucket per step
    assert len(o._flat) == 1


def test_state_dict_roundtrip_fused_unfused():
    """fused -> per-param and per-param -> fused state_dict round-trips
    continue training bit-exact vs an uninterrupted run."""
    def seq(fused_a, fused_b, k=2):
        st.set_flags({"fused_opt": fused_a})
        ps = _params()
        o = opt.AdamW(0.05, parameters=ps, weight_decay=0.1)
        for i, p in enumerate(ps):
            p.name = f"w{i}"
        for i in range(k):
            for p, g in zip(ps, _grads(i)):
                p.grad = pt.to_tensor(g)
            o.step()
            o.clear_grad()
        sd = o.state_dict()
        st.set_flags({"fused_opt": fused_b})
        o2 = opt.AdamW(0.05, parameters=ps, weight_decay=0.1)
        o2.set_state_dict(sd)
        for i in range(k, 2 * k):
            for p, g in zip(ps, _grads(i)):
                p.grad = pt.to_tensor(g)
            o2.step()
            o2.clear_grad()
        return [np.asarray(p._read()) for p in ps]

    base = seq(False, False)
    for a, b in [(True, False), (False, True), (True, True)]:
        got = seq(a, b)
        for x, y in zip(base, got):
            assert np.array_equal(x, y), f"roundtrip {a}->{b} differs"


def test_resume_from_checkpoint_parity():
    """Save/restore mid-run through state_dict (the checkpoint path)
    matches an uninterrupted fused run."""
    def train(o, ps, lo, hi):
        for i in range(lo, hi):
            for p, g in zip(ps, _grads(i)):
                p.grad = pt.to_tensor(g)
            o.step()
            o.clear_grad()

    ps = _params()
    for i, p in enumerate(ps):
        p.name = f"w{i}"
    o = opt.Adam(0.05, parameters=ps)
    train(o, ps, 0, 4)
    ref = [np.asarray(p._read()) for p in ps]

    ps2 = _params()
    for i, p in enumerate(ps2):
        p.name = f"w{i}"
    o2 = opt.Adam(0.05, parameters=ps2)
    train(o2, ps2, 0, 2)
    sd = o2.state_dict()
    wsd = {f"w{i}": pt.Tensor(p._read()) for i, p in enumerate(ps2)}
    # fresh process analog: new params + optimizer, restore both
    ps3 = _params(seed=7)
    for i, p in enumerate(ps3):
        p.name = f"w{i}"
        p._write(wsd[f"w{i}"]._read())
    o3 = opt.Adam(0.05, parameters=ps3)
    o3.set_state_dict(sd)
    train(o3, ps3, 2, 4)
    for x, p in zip(ref, ps3):
        assert np.array_equal(x, np.asarray(p._read()))


# ------------------------------------------------------------- capture --
def test_captured_step_carry_is_flat():
    """A jit-captured train step threads flat buckets, not per-param
    state: the carry is O(buckets), and windows run on it."""
    pt.seed(0)
    net = nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 32),
                        nn.ReLU(), nn.Linear(32, 32), nn.ReLU(),
                        nn.Linear(32, 32), nn.ReLU(), nn.Linear(32, 4))
    o = opt.AdamW(1e-2, parameters=net.parameters())
    n_params = len(net.parameters())
    assert n_params >= 10

    @pt.jit.to_static
    def step(x, y):
        loss = nn.functional.cross_entropy(net(x), y)
        loss.backward()
        o.step()
        o.clear_grad()
        return loss

    rng = np.random.default_rng(0)

    def batch():
        return (pt.to_tensor(rng.normal(size=(4, 8)).astype("float32")),
                pt.to_tensor(rng.integers(0, 4, (4,)).astype("int64")))

    warm = batch()
    step(*warm)
    exe = list(step._cache.values())[0]
    carry_idx, _ = exe.state_split()
    # param flat + master-less fp32: params, m1, m2 buckets + grads
    # + 2 beta pows (+ RNG etc.) — far below per-param counts
    assert len(carry_idx) < n_params, \
        f"carry {len(carry_idx)} not flat (params={n_params})"
    assert len(carry_idx) <= 8
    # windows run unchanged on the flat carry
    batches = [batch() for _ in range(3)]
    ref_losses = [float(step(*b)) for b in batches]
    w = pt.jit.WindowRunner(step, warm, length=3)
    stacks = w.stage([batch() for _ in range(3)])
    outs = w.run(*stacks)
    assert len(outs) == 3 and all(np.isfinite(float(x)) for x in outs)
    assert float(outs[-1]) < ref_losses[0] * 2  # sane continuation


def test_captured_fused_matches_eager_fused():
    pt.seed(3)
    net = nn.Linear(6, 3)
    o = opt.Adam(1e-2, parameters=net.parameters())
    rng = np.random.default_rng(3)
    xs = [rng.normal(size=(4, 6)).astype("float32") for _ in range(4)]

    def loss_step(x):
        loss = (net(x) ** 2).mean()
        loss.backward()
        o.step()
        o.clear_grad()
        return loss

    eager = [float(loss_step(pt.to_tensor(x))) for x in xs[:2]]
    cap = pt.jit.to_static(loss_step)
    compiled = [float(cap(pt.to_tensor(x))) for x in xs[2:]]
    # continue eagerly after compiled steps: state stays coherent
    cont = float(loss_step(pt.to_tensor(xs[0])))
    assert all(np.isfinite(v) for v in eager + compiled + [cont])
    assert cont < eager[0]


def test_hlo_update_op_reduction_10x():
    """Acceptance: traced-step update-op count drops >= 10x at a
    BERT-base-structured param set (size-independent)."""
    import os
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "benchmarks"))
    import optimizer_bench as ob
    shapes = ob.bert_base_shapes(hidden=16, layers=2, vocab=64, seq=16)
    _, arith_fused = ob.hlo_op_counts(shapes, "adamw", fused=True)
    _, arith_pp = ob.hlo_op_counts(shapes, "adamw", fused=False)
    assert arith_pp / max(arith_fused, 1) >= 10.0


# ---------------------------------------------------------------- amp --
def test_grad_scaler_bucketed_unscale_and_skip():
    import paddle_tpu.amp as amp
    ps = _params()
    o = opt.SGD(0.1, parameters=ps)
    # build the buckets with one clean step
    for p, g in zip(ps, _grads(0)):
        p.grad = pt.to_tensor(g)
    o.step()
    o.clear_grad()
    before = [np.asarray(p._read()) for p in ps]
    scaler = amp.GradScaler(init_loss_scaling=1024.0)
    bad = _grads(1)
    bad[1][0] = np.inf
    for p, g in zip(ps, bad):
        p.grad = pt.to_tensor(g)
    scaler.step(o)
    assert scaler._scale == 512.0  # inf seen through the flat bucket
    for x, p in zip(before, ps):
        assert np.array_equal(x, np.asarray(p._read()))  # step skipped


def test_grad_scaler_fused_parity_with_per_param():
    import paddle_tpu.amp as amp

    def run(fused):
        st.set_flags({"fused_opt": fused})
        ps = _params()
        o = opt.SGD(0.1, parameters=ps)
        scaler = amp.GradScaler(init_loss_scaling=8.0)
        for i in range(3):
            for p, g in zip(ps, _grads(i)):
                p.grad = pt.to_tensor(g * 8.0)
            scaler.step(o)
            scaler.update()
            o.clear_grad()
        return [np.asarray(p._read()) for p in ps]

    a, b = run(False), run(True)
    for x, y in zip(a, b):
        assert np.array_equal(x, y)


# ------------------------------------------------------------ guard --
def test_step_guard_bitwise_noop_on_fused_path():
    from paddle_tpu.resilience import StepGuard
    ps = _params()
    o = opt.Adam(0.05, parameters=ps)
    guard = StepGuard(max_bad_steps=3)
    for p, g in zip(ps, _grads(0)):
        p.grad = pt.to_tensor(g)
    loss = pt.to_tensor(np.float32(1.0))
    guard.guarded_step(o, loss)
    o.clear_grad()
    assert o._flat
    snap = [np.asarray(p._read()) for p in ps]
    m_snap = np.asarray(o._accumulators["moment1"][id(ps[0])]._read())
    bad = _grads(1)
    bad[0][0] = np.nan
    for p, g in zip(ps, bad):
        p.grad = pt.to_tensor(g)
    guard.guarded_step(o, pt.to_tensor(np.float32(np.nan)))
    o.clear_grad()
    for x, p in zip(snap, ps):
        assert np.array_equal(x, np.asarray(p._read()))
    assert np.array_equal(
        m_snap, np.asarray(o._accumulators["moment1"][id(ps[0])]._read()))
    assert guard.bad_streak == 1


# ------------------------------------------------------------- comms --
def test_data_parallel_bucketed_grad_sync():
    import paddle_tpu.distributed as dist
    wrapped = dist.DataParallel(nn.Linear(8, 4))
    x = pt.to_tensor(np.random.default_rng(0).normal(
        size=(16, 8)).astype("float32"))
    loss = (wrapped(x) ** 2).mean()
    loss.backward()
    before = [np.asarray(p.grad._read())
              for p in wrapped.parameters() if p.grad is not None]
    wrapped.apply_collective_grads()
    after = [np.asarray(p.grad._read())
             for p in wrapped.parameters() if p.grad is not None]
    # replicated grads: psum-mean is value-preserving, ONE collective
    # for the single fp32 bucket
    for a, b in zip(before, after):
        np.testing.assert_allclose(a, b, rtol=1e-6)
    assert wrapped._last_sync_collectives == 1


def test_data_parallel_sync_uses_fused_flat_buffer():
    import paddle_tpu.distributed as dist
    net = nn.Linear(8, 4)
    wrapped = dist.DataParallel(net)
    o = opt.SGD(0.1, parameters=wrapped.parameters())
    x = pt.to_tensor(np.random.default_rng(1).normal(
        size=(16, 8)).astype("float32"))
    for _ in range(2):
        loss = (wrapped(x) ** 2).mean()
        loss.backward()
        o.step()
        o.clear_grad(set_to_zero=True)
    # grads now live in the optimizer's flat bucket; sync must take the
    # zero-repack path (grad views bound + clean)
    loss = (wrapped(x) ** 2).mean()
    loss.backward()
    o._gather_grads(o._flat[0], {id(p): p.grad for p in o._flat[0].params})
    wrapped.apply_collective_grads()
    assert wrapped._last_sync_collectives == 1


# ------------------------------------------------------------ pallas --
def test_pallas_kernel_matches_jnp_twin():
    from paddle_tpu.ops.pallas import fused_optimizer as fo
    import jax.numpy as jnp
    n = 2048
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.normal(size=(n,)).astype("float32"))
    g = jnp.asarray(rng.integers(-3, 4, (n,)).astype("float32"))
    m = jnp.zeros((n,), jnp.float32)
    v = jnp.zeros((n,), jnp.float32)
    spec = fo.UpdateSpec(kind="adamw", decay=0.1, has_clip=True)
    kw = dict(w=w, g=g, m=m, v=v, b1p=jnp.float32(1.0),
              b2p=jnp.float32(1.0), lr=1e-2, clip_scale=0.5)
    a = fo.fused_update(spec, impl="jnp", **kw)
    b = fo.fused_update(spec, impl="pallas_interpret", **kw)
    for x, y in zip(a, b):
        if x is None:
            assert y is None
            continue
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   rtol=1e-6, atol=1e-7)


def test_env_flag_forces_per_param():
    st.set_flags({"fused_opt": False})
    ps = _params()
    o = opt.Adam(0.01, parameters=ps)
    for p, g in zip(ps, _grads(0)):
        p.grad = pt.to_tensor(g)
    o.step()
    assert o._flat is None
    assert ps[0]._flat_view is None


def test_exotic_params_fall_back_automatically():
    """Per-param LR / per-param regularizer params stay on the
    per-param path (leftovers) while the rest fuse."""
    ps = _params()
    ps[1].optimize_attr["learning_rate"] = 0.5
    o = opt.Adam(0.05, parameters=ps)
    for p, g in zip(ps, _grads(0)):
        p.grad = pt.to_tensor(g)
    o.step()
    assert o._flat and len(o._flat[0].params) == 2
    assert ps[1]._flat_view is None

    # per-param parity for the mixed step
    st.set_flags({"fused_opt": False})
    ps2 = _params()
    ps2[1].optimize_attr["learning_rate"] = 0.5
    o2 = opt.Adam(0.05, parameters=ps2)
    for p, g in zip(ps2, _grads(0)):
        p.grad = pt.to_tensor(g)
    o2.step()
    for a, b in zip(ps, ps2):
        assert np.array_equal(np.asarray(a._read()), np.asarray(b._read()))


def test_mid_run_disable_folds_beta_pows_back():
    """Flipping the flag off after fused Adam steps must defuse (folding
    the per-bucket beta-pow scalars back into per-param accumulators) so
    the per-param path's bias correction continues, not restarts."""
    import warnings

    def run(off_at=None, steps=6):
        st.set_flags({"fused_opt": True})
        ps = _params()
        o = opt.Adam(0.05, parameters=ps)
        for i in range(steps):
            if off_at is not None and i == off_at:
                st.set_flags({"fused_opt": False})
            for p, g in zip(ps, _grads(i)):
                p.grad = pt.to_tensor(g)
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                o.step()
            o.clear_grad()
        return [np.asarray(p._read()) for p in ps]

    ref = run()
    mixed = run(off_at=3)
    for a, b in zip(ref, mixed):
        assert np.array_equal(a, b)


def test_capture_step_only_with_clean_prebound_grads():
    """A captured function that ONLY calls step() (grads already bound
    as clean flat views by prior eager fused steps) must compile: the
    gather short-circuit is eager-only, so discovery and replay read the
    same member grads."""
    import warnings

    ps = _params()
    o = opt.AdamW(0.05, parameters=ps)
    for i in range(2):  # eager fused steps bind grad views
        for p, g in zip(ps, _grads(0)):
            p.grad = pt.to_tensor(g)
        o.step()
        if i == 0:
            o.clear_grad()

    @pt.jit.to_static
    def just_step():
        o.step()
        return ps[0]

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        just_step()
        just_step()
    assert not any("eager fallback" in str(x.message) for x in w)


def test_guarded_eager_step_keeps_buckets_clean():
    """StepGuard's blend must write bucket STORAGES, not the per-param
    views — a view write would mark local overrides and force a full
    per-member re-sync (concat) of every bucket on the next step."""
    from paddle_tpu.resilience import StepGuard
    ps = _params()
    o = opt.AdamW(0.05, parameters=ps)
    guard = StepGuard(max_bad_steps=3)
    for i in range(2):
        for p, g in zip(ps, _grads(i)):
            p.grad = pt.to_tensor(g)
        guard.guarded_step(o, pt.to_tensor(np.float32(1.0)))
        o.clear_grad()
    assert o._flat
    for grp in o._flat:
        for store in grp.stores():
            assert not store._dirty
            assert not any(store.local)


def test_bf16_moment_optimizers_without_master_stay_per_param():
    """Flat moment stores are f32; without master weights the per-param
    path keeps accumulators in the param dtype — those buckets must not
    fuse (history-independent), while moment-free SGD still does."""
    st.set_flags({"fused_opt": True})
    ps = _params(dtype="bfloat16")
    o = opt.Momentum(0.1, 0.9, parameters=ps)  # no multi_precision
    for p, g in zip(ps, _grads(0)):
        p.grad = pt.to_tensor(g.astype("bfloat16"))
    o.step()
    assert o._flat is None
    assert ps[0]._flat_view is None

    ps2 = _params(dtype="bfloat16")
    o2 = opt.SGD(0.1, parameters=ps2)  # no moments: fusing stays exact
    for p, g in zip(ps2, _grads(0)):
        p.grad = pt.to_tensor(g.astype("bfloat16"))
    o2.step()
    assert o2._flat


def test_param_view_write_in_capture_declines_to_eager():
    """A captured step that writes a param view (e.g. weight decay /
    EMA-style mutation before step()) cannot compile on the fused path:
    discovery folds the override and resets the dirty flag, so a
    compiled program would silently drop the write. The replay-phase
    GraphBreak must decline capture so every call stays bitwise equal
    to the per-param EAGER reference."""
    import warnings

    def run(fused, capture):
        st.set_flags({"fused_opt": fused})
        ps = _params()
        o = opt.AdamW(0.05, parameters=ps)

        def body():
            ps[0]._write(ps[0]._read() * 0.9)
            for p, g in zip(ps, _grads(0)):
                p.grad = pt.to_tensor(g)
            o.step()
            o.clear_grad()
            return ps[0]

        fn = pt.jit.to_static(body) if capture else body
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            for _ in range(3):
                fn()
        declined = any("eager fallback" in str(x.message) or
                       "pinning" in str(x.message) for x in w)
        return [np.asarray(p._read()) for p in ps], declined

    got, declined = run(fused=True, capture=True)
    ref, _ = run(fused=False, capture=False)
    assert declined, "fused path must decline the capture"
    for a, b in zip(got, ref):
        assert np.array_equal(a, b)

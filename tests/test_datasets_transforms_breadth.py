"""Round-3 breadth: DatasetFolder/ImageFolder/Flowers/VOC2012 datasets and
distribution transforms (VERDICT r2 missing #5/#6)."""
import io
import os
import tarfile

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import distribution as D


def _png_bytes(arr):
    from PIL import Image
    buf = io.BytesIO()
    Image.fromarray(arr).save(buf, format="PNG")
    return buf.getvalue()


def _jpg_bytes(arr):
    from PIL import Image
    buf = io.BytesIO()
    Image.fromarray(arr).save(buf, format="JPEG")
    return buf.getvalue()


# ------------------------------------------------------------ datasets ----

def test_dataset_folder(tmp_path):
    from paddle_tpu.vision.datasets import DatasetFolder
    rng = np.random.default_rng(0)
    for cls in ("cat", "dog"):
        d = tmp_path / cls
        d.mkdir()
        for i in range(3):
            (d / f"{i}.png").write_bytes(_png_bytes(
                rng.integers(0, 255, (8, 8, 3)).astype(np.uint8)))
    ds = DatasetFolder(str(tmp_path))
    assert ds.classes == ["cat", "dog"]
    assert len(ds) == 6
    img, target = ds[0]
    assert img.shape == (8, 8, 3) and target == 0
    assert sorted(set(ds.targets)) == [0, 1]


def test_image_folder_and_transform(tmp_path):
    from paddle_tpu.vision.datasets import ImageFolder
    (tmp_path / "a.png").write_bytes(_png_bytes(
        np.zeros((6, 6, 3), np.uint8)))
    (tmp_path / "skip.txt").write_text("not an image")
    ds = ImageFolder(str(tmp_path),
                     transform=lambda im: im.astype("float32") / 255)
    assert len(ds) == 1
    (img,) = ds[0]
    assert img.dtype == np.float32


def test_flowers_dataset(tmp_path):
    from paddle_tpu.vision.datasets import Flowers
    from scipy.io import savemat
    rng = np.random.default_rng(1)
    tgz = tmp_path / "102flowers.tgz"
    with tarfile.open(tgz, "w:gz") as tf:
        for i in range(1, 5):
            data = _jpg_bytes(rng.integers(0, 255, (10, 10, 3))
                              .astype(np.uint8))
            info = tarfile.TarInfo(f"jpg/image_{i:05d}.jpg")
            info.size = len(data)
            tf.addfile(info, io.BytesIO(data))
    savemat(tmp_path / "imagelabels.mat",
            {"labels": np.array([[1, 2, 1, 3]])})
    savemat(tmp_path / "setid.mat",
            {"trnid": np.array([[1, 3]]), "valid": np.array([[2]]),
             "tstid": np.array([[4]])})
    ds = Flowers(data_file=str(tgz),
                 label_file=str(tmp_path / "imagelabels.mat"),
                 setid_file=str(tmp_path / "setid.mat"), mode="train")
    assert len(ds) == 2
    img, label = ds[0]
    assert img.shape == (10, 10, 3) and int(label) == 0  # 1 -> 0-based


def test_voc2012_dataset(tmp_path):
    from paddle_tpu.vision.datasets import VOC2012
    rng = np.random.default_rng(2)
    tar = tmp_path / "voc.tar"
    root = "VOCdevkit/VOC2012/"
    with tarfile.open(tar, "w") as tf:
        def add(name, data):
            info = tarfile.TarInfo(name)
            info.size = len(data)
            tf.addfile(info, io.BytesIO(data))
        add(root + "ImageSets/Segmentation/train.txt", b"img1\n")
        add(root + "ImageSets/Segmentation/val.txt", b"img1\n")
        add(root + "ImageSets/Segmentation/trainval.txt", b"img1\n")
        add(root + "JPEGImages/img1.jpg", _jpg_bytes(
            rng.integers(0, 255, (12, 12, 3)).astype(np.uint8)))
        add(root + "SegmentationClass/img1.png", _png_bytes(
            rng.integers(0, 20, (12, 12)).astype(np.uint8)))
    ds = VOC2012(data_file=str(tar), mode="train")
    assert len(ds) == 1
    img, mask = ds[0]
    assert img.shape == (12, 12, 3) and mask.shape == (12, 12)


# ----------------------------------------------------------- transforms ----

def _np_t(x):
    return paddle.to_tensor(np.asarray(x, np.float32))


def test_affine_exp_sigmoid_tanh_roundtrip_and_jacobian():
    x = np.linspace(-2, 2, 9).astype(np.float32)
    for t, dydx in [
        (D.AffineTransform(1.0, 3.0), lambda x: 3.0 * np.ones_like(x)),
        (D.ExpTransform(), np.exp),
        (D.SigmoidTransform(),
         lambda x: 1 / (1 + np.exp(-x)) * (1 - 1 / (1 + np.exp(-x)))),
        (D.TanhTransform(), lambda x: 1 - np.tanh(x) ** 2),
        (D.PowerTransform(2.0), lambda x: 2 * np.abs(x)),
    ]:
        xs = np.abs(x) + 0.1 if isinstance(
            t, (D.PowerTransform,)) else x
        y = t.forward(_np_t(xs)).numpy()
        back = t.inverse(paddle.to_tensor(y)).numpy()
        np.testing.assert_allclose(back, xs, rtol=1e-4, atol=1e-5)
        ld = t.forward_log_det_jacobian(_np_t(xs)).numpy()
        np.testing.assert_allclose(ld, np.log(np.abs(dydx(xs))),
                                   rtol=1e-4, atol=1e-5)
        # inverse_log_det = -forward_log_det at the preimage
        ild = t.inverse_log_det_jacobian(paddle.to_tensor(y)).numpy()
        np.testing.assert_allclose(ild, -ld, rtol=1e-4, atol=1e-5)


def test_chain_transform():
    t = D.ChainTransform([D.AffineTransform(0.0, 2.0), D.ExpTransform()])
    x = np.array([0.0, 1.0], np.float32)
    y = t.forward(_np_t(x)).numpy()
    np.testing.assert_allclose(y, np.exp(2 * x), rtol=1e-5)
    np.testing.assert_allclose(t.inverse(paddle.to_tensor(y)).numpy(), x,
                               rtol=1e-5)
    ld = t.forward_log_det_jacobian(_np_t(x)).numpy()
    np.testing.assert_allclose(ld, np.log(2.0) + 2 * x, rtol=1e-5)


def test_stack_and_reshape_and_independent():
    st = D.StackTransform([D.ExpTransform(), D.AffineTransform(0.0, -2.0)],
                          axis=0)
    x = np.stack([np.ones(3, np.float32), np.ones(3, np.float32)])
    y = st.forward(_np_t(x)).numpy()
    np.testing.assert_allclose(y[0], np.e * np.ones(3), rtol=1e-5)
    np.testing.assert_allclose(y[1], -2 * np.ones(3), rtol=1e-5)

    rt = D.ReshapeTransform((6,), (2, 3))
    z = rt.forward(_np_t(np.arange(6))).numpy()
    assert z.shape == (2, 3)
    assert rt.forward_shape((5, 6)) == (5, 2, 3)
    assert rt.inverse_shape((5, 2, 3)) == (5, 6)

    it = D.IndependentTransform(D.ExpTransform(), 1)
    ld = it.forward_log_det_jacobian(_np_t(np.ones((4, 3)))).numpy()
    assert ld.shape == (4,)
    np.testing.assert_allclose(ld, 3.0 * np.ones(4), rtol=1e-5)


def test_stick_breaking_simplex():
    t = D.StickBreakingTransform()
    x = np.random.default_rng(0).normal(size=(5, 4)).astype(np.float32)
    y = t.forward(_np_t(x)).numpy()
    assert y.shape == (5, 5)
    assert (y > 0).all()
    np.testing.assert_allclose(y.sum(-1), np.ones(5), rtol=1e-5)
    np.testing.assert_allclose(t.inverse(paddle.to_tensor(y)).numpy(), x,
                               rtol=1e-3, atol=1e-4)


def test_transformed_distribution_lognormal():
    """Normal pushed through Exp == LogNormal: log_prob and samples."""
    paddle.seed(0)
    base = D.Normal(0.0, 1.0)
    td = D.TransformedDistribution(base, [D.ExpTransform()])
    v = np.array([0.5, 1.0, 2.5], np.float32)
    got = td.log_prob(paddle.to_tensor(v)).numpy()
    ref = D.LogNormal(0.0, 1.0).log_prob(paddle.to_tensor(v)).numpy()
    np.testing.assert_allclose(got, ref, rtol=1e-4)
    s = td.sample((1000,)).numpy()
    assert (s > 0).all()


def test_transform_call_on_distribution():
    td = D.ExpTransform()(D.Normal(0.0, 1.0))
    assert isinstance(td, D.TransformedDistribution)

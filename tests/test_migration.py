"""Live request migration & graceful drain (ISSUE 20).

Acceptance model: a request migrated between serving replicas
MID-FLIGHT — queued, mid-prefill, or mid-decode; fp or kv-quantized
pools; shared-prefix/COW pages; TP-sharded source and destination —
must produce EXACTLY the token stream of the unmigrated run (greedy
decode is deterministic and batch-invariant; the snapshot carries the
token prefix, so the restored KV bytes are the same pure function of
it).  On top of the bitwise bar: ``FleetRouter.drain`` must complete
without waiting out resident decodes (warm handoff, not a cold wait),
a planned preemption (SIGTERM through ``resilience.preempt``) must
lame-duck a replica and lose zero prefill work, a transfer that fails
past the retry budget must fall back to the PR17 cold requeue under
exactly one coded PDT-E025 flight record with demand counted once, a
torn (CRC-invalid) snapshot must be rejected at restore with the
source still serving, and a raced ``cancel`` must surface exactly one
``cancelled`` completion.  Pool conservation holds on every engine on
both sides of every move.

Shares the session ``serving_gpt`` and the serving-suite geometry, so
the compiled programs come off the session model's cache.
"""
import json
import os
import signal

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core import errors
from paddle_tpu.inference import ContinuousBatchingEngine, FleetRouter
from paddle_tpu.resilience import faults, preempt

from test_serving_engine import _assert_pool_conserved

# ONE geometry for the whole module — matches test_serving_engine's /
# test_router's, so every engine reuses the session model's compiled
# serving programs
KW = dict(max_slots=2, page_size=8, max_seq_len=32, decode_window=4,
          prefill_chunk=8, q_block=2)


@pytest.fixture(scope="module")
def gpt(serving_gpt):
    return serving_gpt


@pytest.fixture(scope="module")
def mesh2():
    import jax
    from jax.sharding import Mesh
    return Mesh(np.asarray(jax.devices()[:2]), ("tp",))


def _workload(seed=0, sizes=(12, 9, 14), new=(8, 8, 8)):
    rng = np.random.default_rng(seed)
    return ([rng.integers(1, 96, (n,)).astype(np.int32)
             for n in sizes], list(new))


def _ref_stream(gpt, prompt, new, **kw):
    eng = ContinuousBatchingEngine(gpt, **{**KW, **kw})
    rid = eng.add_request(prompt, new)
    done = eng.run()
    _assert_pool_conserved(eng)
    return done[rid].sequence


def _migrate_mid_decode(src, dst, rid, min_done=2, max_steps=200):
    """Step ``src`` until ``rid`` is mid-decode with ``min_done``
    tokens emitted, then snapshot -> restore -> discard.  Returns the
    shipped payload."""
    payload = None
    for _ in range(max_steps):
        src.step()
        try:
            p = src.snapshot_request(rid)
        except (KeyError, ValueError):
            continue
        if p["phase"] == "decode" and len(p["done_toks"]) >= min_done:
            payload = p
            break
    assert payload is not None, "request never reached mid-decode"
    got = dst.restore_request(payload)
    assert got == rid
    assert src.discard_request(rid) is True
    return payload


# =============================================== engine-level moves ==

def test_migrate_mid_decode_bitwise(gpt):
    """The core claim: a stream migrated mid-decode equals the
    unmigrated stream token-for-token, both pools conserved, and the
    migration counters tell the story on each side."""
    prompts, new = _workload()
    ref = _ref_stream(gpt, prompts[0], new[0])
    src = ContinuousBatchingEngine(gpt, **KW)
    dst = ContinuousBatchingEngine(gpt, **KW)
    rid = src.add_request(prompts[0], new[0])
    payload = _migrate_mid_decode(src, dst, rid)
    assert payload["n_pages"] >= 1 and payload["pools"]
    done = dst.run()
    np.testing.assert_array_equal(done[rid].sequence, ref)
    assert done[rid].finish_reason == "length"
    src.run()
    _assert_pool_conserved(src)
    _assert_pool_conserved(dst)
    assert src.stats["migrated_out"] == 1
    assert src.stats["migrated_in"] == 0
    assert dst.stats["migrated_in"] == 1


def test_migrate_queued_and_mid_prefill(gpt):
    """A QUEUED request snapshots without pools and restores through
    the ordinary admission path; a MID-PREFILL request ships its
    finished chunks warm — the destination computes only the remaining
    prefill tokens (zero prefill work lost), stream bitwise."""
    prompts, new = _workload(seed=4, sizes=(20, 6), new=(6, 4))
    ref = _ref_stream(gpt, prompts[0], new[0])
    # queued: snapshot before any step admits it
    src = ContinuousBatchingEngine(gpt, **KW)
    rid = src.add_request(prompts[0], new[0])
    pay = src.snapshot_request(rid)
    assert pay["phase"] == "queued" and not pay["pools"]
    dst = ContinuousBatchingEngine(gpt, **KW)
    assert dst.restore_request(pay) == rid
    assert src.discard_request(rid) is True
    done = dst.run()
    np.testing.assert_array_equal(done[rid].sequence, ref)
    assert not src.has_work
    # mid-prefill: 20-token prompt, 8-token chunks -> step once so one
    # or two chunks are resident, then move the request warm
    src2 = ContinuousBatchingEngine(gpt, **KW)
    rid2 = src2.add_request(prompts[0], new[0])
    pay2 = None
    for _ in range(50):
        src2.step()
        try:
            p = src2.snapshot_request(rid2)
        except (KeyError, ValueError):
            continue
        if p["phase"] == "prefill" and p["prefill_off"] > 0:
            pay2 = p
            break
    assert pay2 is not None, "never caught the request mid-prefill"
    dst2 = ContinuousBatchingEngine(gpt, **KW)
    assert dst2.restore_request(pay2) == rid2
    assert src2.discard_request(rid2) is True
    done2 = dst2.run()
    np.testing.assert_array_equal(done2[rid2].sequence, ref)
    # the destination re-prefilled ONLY the unfinished suffix
    assert (dst2.stats["prefill_tokens_computed"]
            <= prompts[0].size - pay2["prefill_off"] + KW["page_size"])
    _assert_pool_conserved(src2)
    _assert_pool_conserved(dst2)


def test_migrate_kv_quant_bitwise(gpt):
    """Quantized KV pools (value + scale side-pools) ship and restore
    bitwise; a layout mismatch (fp destination) refuses coded."""
    prompts, new = _workload(seed=5)
    ref = _ref_stream(gpt, prompts[0], new[0], kv_quant=True)
    src = ContinuousBatchingEngine(gpt, kv_quant=True, **KW)
    dst = ContinuousBatchingEngine(gpt, kv_quant=True, **KW)
    rid = src.add_request(prompts[0], new[0])
    _migrate_mid_decode(src, dst, rid)
    done = dst.run()
    np.testing.assert_array_equal(done[rid].sequence, ref)
    src.run()
    _assert_pool_conserved(src)
    _assert_pool_conserved(dst)


def test_migrate_shared_prefix_cow_warm_destination(gpt):
    """Shared-prefix traffic: the destination already serves the same
    8-token prefix, so the restored request's prefix pages come off
    the destination's radix cache (COW at the divergence page) — the
    migrated stream is still bitwise and both pools conserve."""
    rng = np.random.default_rng(11)
    prefix = rng.integers(1, 96, 8).astype(np.int32)
    member = np.concatenate([prefix,
                             rng.integers(1, 96, 6).astype(np.int32)])
    leader = np.concatenate([prefix,
                             rng.integers(1, 96, 4).astype(np.int32)])
    ref = _ref_stream(gpt, member, 6)
    src = ContinuousBatchingEngine(gpt, **KW)
    dst = ContinuousBatchingEngine(gpt, **KW)
    dst.add_request(leader, 4)
    dst.run()                      # warm the destination's prefix cache
    rid = src.add_request(member, 6)
    _migrate_mid_decode(src, dst, rid)
    done = dst.run()
    np.testing.assert_array_equal(done[rid].sequence, ref)
    src.run()
    _assert_pool_conserved(src)
    _assert_pool_conserved(dst)


@pytest.mark.skipif("XLA_FLAGS" not in os.environ
                    or "host_platform_device_count" not in
                    os.environ.get("XLA_FLAGS", ""),
                    reason="needs the 8-device CPU mesh")
def test_migrate_tp2_to_tp2_bitwise(gpt, mesh2):
    """TP=2 source -> TP=2 destination: sharded pools gather into the
    snapshot, the restore re-shards through the import scatter's
    out_shardings, and the stream is bitwise the unsharded one."""
    prompts, new = _workload(seed=6)
    ref = _ref_stream(gpt, prompts[0], new[0])
    src = ContinuousBatchingEngine(gpt, mesh=mesh2, **KW)
    dst = ContinuousBatchingEngine(gpt, mesh=mesh2, **KW)
    rid = src.add_request(prompts[0], new[0])
    _migrate_mid_decode(src, dst, rid)
    done = dst.run()
    np.testing.assert_array_equal(done[rid].sequence, ref)
    src.run()
    _assert_pool_conserved(src)
    _assert_pool_conserved(dst)


def test_torn_snapshot_rejected_source_keeps(gpt):
    """The engine_snapshot_torn drill: a CRC-invalid payload is
    REJECTED at restore (MigrationError PDT-E025) — nothing lands on
    the destination, and the source (which never discarded) finishes
    the request normally."""
    prompts, new = _workload(seed=7)
    ref = _ref_stream(gpt, prompts[0], new[0])
    src = ContinuousBatchingEngine(gpt, **KW)
    dst = ContinuousBatchingEngine(gpt, **KW)
    rid = src.add_request(prompts[0], new[0])
    payload = None
    for _ in range(200):
        src.step()
        try:
            p = src.snapshot_request(rid)
        except (KeyError, ValueError):
            continue
        if p["phase"] == "decode" and len(p["done_toks"]) >= 2:
            payload = p
            break
    assert payload is not None
    faults.clear()
    faults.inject("engine_snapshot_torn", str(rid), times=1)
    try:
        with pytest.raises(errors.MigrationError) as ei:
            dst.restore_request(payload)
    finally:
        faults.clear()
    assert "PDT-E025" in str(ei.value)
    assert dst.stats["migrated_in"] == 0
    assert not dst.has_work
    _assert_pool_conserved(dst)
    done = src.run()               # source never stopped serving it
    np.testing.assert_array_equal(done[rid].sequence, ref)
    _assert_pool_conserved(src)


def test_cancel_race_exactly_one_cancelled(gpt):
    """Regression (ISSUE 20 bugfix): ``cancel(rid)`` racing an
    in-flight migration honors ``finish_reason="cancelled"`` on
    exactly one side — the source defers to its sweep (``discard``
    returns False) and the destination drops the restore."""
    prompts, new = _workload(seed=8)
    src = ContinuousBatchingEngine(gpt, **KW)
    dst = ContinuousBatchingEngine(gpt, **KW)
    rid = src.add_request(prompts[0], new[0])
    payload = None
    for _ in range(200):
        src.step()
        try:
            p = src.snapshot_request(rid)
        except (KeyError, ValueError):
            continue
        if p["phase"] == "decode" and len(p["done_toks"]) >= 2:
            payload = p
            break
    assert payload is not None
    got = dst.restore_request(payload)      # transfer already landed
    assert got == rid
    assert src.cancel(rid) is True          # ...when the cancel races
    # the source now refuses the discard: its sweep owns the finish
    assert src.discard_request(rid) is False
    assert dst.discard_request(rid) is True  # destination drops it
    done_src = src.run()
    done_dst = dst.run()
    cancelled = [c for c in list(done_src.values())
                 + list(done_dst.values())
                 if c.finish_reason == "cancelled"]
    assert len(cancelled) == 1 and cancelled[0].request_id == rid
    assert not done_dst                      # nothing finished there
    _assert_pool_conserved(src)
    _assert_pool_conserved(dst)
    # a snapshot taken AFTER the cancel refuses coded: migration must
    # skip a cancelling request, the sweep finalizes it
    src2 = ContinuousBatchingEngine(gpt, **KW)
    rid2 = src2.add_request(prompts[1], new[1])
    for _ in range(3):
        src2.step()
    assert src2.cancel(rid2) is True
    with pytest.raises(ValueError):
        src2.snapshot_request(rid2)
    src2.run()
    _assert_pool_conserved(src2)


# ================================================ router-level flow ==

def _fleet_pool_conserved(router):
    for rep in router._replicas:
        if rep.state != "dead" and hasattr(rep.engine, "_free_pages"):
            _assert_pool_conserved(rep.engine)


def _drive_fleet(gpt, prompts, new, drain_at=None, drain_name="r0",
                 **rkw):
    r = FleetRouter(gpt, replicas=2, replica_kwargs=KW,
                    heartbeat_timeout_ms=0, **rkw)
    rids = [r.add_request(p, n) for p, n in zip(prompts, new)]
    done, steps = {}, 0
    while r.has_work:
        if drain_at is not None and steps == drain_at:
            assert r.drain(drain_name) is True
        for c in r.step():
            done[c.request_id] = c
        steps += 1
        assert steps < 2000, "fleet wedged"
    return r, rids, done


def test_router_drain_migrates_without_waiting(gpt):
    """Drain under load: the drained replica's residents move warm to
    the survivor mid-decode (migrations counted, pages shipped), every
    stream is bitwise the undrained run, the drained replica parks in
    standby, and no engine leaks a page."""
    prompts, new = _workload()
    r0, rids0, base = _drive_fleet(gpt, prompts, new, migration=False)
    r, rids, done = _drive_fleet(gpt, prompts, new, drain_at=3,
                                 migration=True)
    assert sorted(done) == sorted(rids)
    for a, b in zip(rids, rids0):
        np.testing.assert_array_equal(done[a].sequence,
                                      base[b].sequence)
    st = r.stats
    assert st["migrations"] >= 1 and st["migrated_pages"] >= 1
    assert st["migration_failures"] == 0 and st["deaths"] == 0
    assert r.replica_states()["r0"] == "standby"
    _fleet_pool_conserved(r)
    # the migrated requests FINISHED on the survivor, not the source
    assert r.replica("r0").stats["migrated_out"] >= 1
    assert r.replica("r1").stats["migrated_in"] >= 1


def test_router_migration_transient_absorbed(gpt):
    """The router_migration_transient drill inside the retry budget:
    the bounded envelope absorbs it (retry counter moves, zero
    failures) and the drained run stays bitwise."""
    prompts, new = _workload()
    _, rids0, base = _drive_fleet(gpt, prompts, new, migration=False)
    faults.clear()
    faults.inject("router_migration_transient", times=2)
    try:
        r, rids, done = _drive_fleet(gpt, prompts, new, drain_at=3,
                                     migration=True,
                                     migration_retries=3)
    finally:
        faults.clear()
    for a, b in zip(rids, rids0):
        np.testing.assert_array_equal(done[a].sequence,
                                      base[b].sequence)
    assert r.stats["migration_retries"] >= 2
    assert r.stats["migration_failures"] == 0
    assert r.stats["migrations"] >= 1
    _fleet_pool_conserved(r)


def test_router_migration_past_budget_cold_requeue(gpt, tmp_path,
                                                   monkeypatch):
    """Past the budget: the transfer gives up, ONE coded PDT-E025
    flight record per failed move is written, the request falls back
    to the PR17 cold requeue (front of its tenant queue) and completes
    bitwise — demand counted once (the fleet-wide requested total
    matches the clean run), zero leaked pages on either engine."""
    monkeypatch.setenv("PDTPU_FLIGHT_DIR", str(tmp_path))
    prompts, new = _workload()
    rc, rids0, base = _drive_fleet(gpt, prompts, new, migration=False)
    req_clean = sum(rep.engine.stats["prefill_tokens_requested"]
                    for rep in rc._replicas)
    faults.clear()
    faults.inject("router_migration_transient", times=100)
    try:
        r, rids, done = _drive_fleet(gpt, prompts, new, drain_at=3,
                                     migration=True,
                                     migration_retries=1)
    finally:
        faults.clear()
    assert sorted(done) == sorted(rids)
    for a, b in zip(rids, rids0):
        np.testing.assert_array_equal(done[a].sequence,
                                      base[b].sequence)
    st = r.stats
    assert st["migrations"] == 0 and st["migration_failures"] >= 1
    assert st["requeues"] >= 1 and st["deaths"] == 0
    # demand counted once through the cold fallback (requeue=True)
    req_fault = sum(rep.engine.stats["prefill_tokens_requested"]
                    for rep in r._replicas)
    assert req_fault == req_clean
    _fleet_pool_conserved(r)
    recs = [f for f in sorted(os.listdir(tmp_path))
            if f.endswith(".json") and not f.endswith(".trace.json")]
    fails = []
    for f in recs:
        rec = json.load(open(os.path.join(tmp_path, f)))
        if rec.get("reason") == "router_migration_failed":
            fails.append(rec)
    assert len(fails) == st["migration_failures"]  # exactly one each
    for rec in fails:
        assert rec["error_code"] == "PDT-E025"
        assert rec["extra"]["fallback"] == "cold_requeue"


def test_router_torn_snapshot_falls_back(gpt):
    """Torn payload at the fleet level: the restore rejects, the
    source keeps serving (no requeue, no loss), the run is bitwise."""
    prompts, new = _workload()
    _, rids0, base = _drive_fleet(gpt, prompts, new, migration=False)
    faults.clear()
    faults.inject("engine_snapshot_torn", times=1)
    try:
        r, rids, done = _drive_fleet(gpt, prompts, new, drain_at=3,
                                     migration=True)
    finally:
        faults.clear()
    assert sorted(done) == sorted(rids)
    for a, b in zip(rids, rids0):
        np.testing.assert_array_equal(done[a].sequence,
                                      base[b].sequence)
    assert r.stats["migration_failures"] >= 1
    _fleet_pool_conserved(r)


def test_lameduck_sigterm_drill(gpt):
    """Planned preemption: SIGTERM through ``resilience.preempt`` puts
    the last live replica (never the last standing) into lame-duck —
    placements stop, residents migrate warm, the duck parks in standby
    — and every stream is bitwise the unpreempted run."""
    prompts, new = _workload()
    _, rids0, base = _drive_fleet(gpt, prompts, new, migration=False)
    assert preempt.install() is True
    try:
        r = FleetRouter(gpt, replicas=2, replica_kwargs=KW,
                        heartbeat_timeout_ms=0, migration=True)
        rids = [r.add_request(p, n) for p, n in zip(prompts, new)]
        done, steps = {}, 0
        while r.has_work:
            if steps == 3:
                signal.raise_signal(signal.SIGTERM)
            for c in r.step():
                done[c.request_id] = c
            steps += 1
            assert steps < 2000, "preempt drill wedged"
    finally:
        preempt.uninstall()
        preempt.clear()
    assert sorted(done) == sorted(rids)
    for a, b in zip(rids, rids0):
        np.testing.assert_array_equal(done[a].sequence,
                                      base[b].sequence)
    assert r.stats["lameducks"] == 1
    assert r.replica_states()["r1"] == "standby"
    assert r.replica_states()["r0"] == "live"  # never the last one
    _fleet_pool_conserved(r)


def test_drain_under_storm_demand_counted_once(gpt):
    """Drain while a storm is still arriving: new placements avoid the
    draining replica, migrated + fresh requests all complete bitwise
    vs the drain-free storm, and warm moves re-prefill nothing (the
    fleet-wide requested total matches the clean run)."""
    prompts, new = _workload(seed=9, sizes=(12, 9, 14, 6, 10),
                             new=(6, 6, 6, 4, 4))

    def drive(drain):
        # 3 replicas: the survivors must have slot headroom while the
        # storm keeps arriving, or the warm move has nowhere to land
        r = FleetRouter(gpt, replicas=3, replica_kwargs=KW,
                        heartbeat_timeout_ms=0, migration=True)
        rids = [r.add_request(p, n)
                for p, n in zip(prompts[:3], new[:3])]
        pending = list(zip(prompts[3:], new[3:]))
        done, steps = {}, 0
        while r.has_work or pending:
            if drain and steps == 3:
                assert r.drain("r0") is True
            if pending and steps >= 2:
                p, n = pending.pop(0)
                rids.append(r.add_request(p, n))
            for c in r.step():
                done[c.request_id] = c
            steps += 1
            assert steps < 2000
        req = sum(rep.engine.stats["prefill_tokens_requested"]
                  for rep in r._replicas)
        return r, rids, done, req

    rc, rids_c, done_c, req_c = drive(False)
    rd, rids_d, done_d, req_d = drive(True)
    assert sorted(done_c) == sorted(rids_c)
    assert sorted(done_d) == sorted(rids_d)
    for a, b in zip(rids_c, rids_d):
        np.testing.assert_array_equal(done_c[a].sequence,
                                      done_d[b].sequence)
    assert rd.stats["migrations"] >= 1
    assert req_d == req_c                    # warm moves re-prefill 0
    _fleet_pool_conserved(rd)


# ======================================================== benches ==

def test_serving_bench_migration_smoke(gpt):
    """The serving_bench ``migration`` columns on the CPU tiny model:
    migrate-drain beats (or at worst matches, on this tiny workload)
    the cold wait on drain latency, pages actually ship, prefill
    tokens are saved, and the streams gate bitwise (absolute times are
    TPU claims)."""
    import sys
    sys.path.insert(0, "/root/repo/benchmarks")
    import serving_bench as sb
    cfg = gpt.cfg
    row = sb._measure_migration(cfg, gpt, prompt_len=16, new_tokens=6,
                                n_requests=3, page_size=8,
                                decode_window=4, prefill_chunk=8,
                                max_seq_len=32, q_block=2, warm=False)
    assert row["outputs_equal"]
    assert row["migrated_pages"] >= 1
    assert row["pages_leaked"] == 0
    assert row["drain_ms_migrate"] > 0.0 and row["drain_ms_wait"] > 0.0

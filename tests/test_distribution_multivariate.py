"""MultivariateNormal / ContinuousBernoulli / Independent /
ExponentialFamily (reference ``python/paddle/distribution/
multivariate_normal.py``, ``continuous_bernoulli.py``,
``independent.py``, ``exponential_family.py``)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distribution import (
    ContinuousBernoulli, ExponentialFamily, Independent,
    MultivariateNormal, Normal, kl_divergence,
)


def _mvn_ref_logpdf(x, loc, C):
    k = len(loc)
    d = x - loc
    return float(-0.5 * (k * np.log(2 * np.pi)
                         + np.log(np.linalg.det(C))
                         + d @ np.linalg.solve(C, d)))


@pytest.fixture
def mvn_setup():
    rng = np.random.default_rng(0)
    A = rng.normal(size=(3, 3))
    C = (A @ A.T + 3 * np.eye(3)).astype(np.float32)
    loc = rng.normal(size=3).astype(np.float32)
    x = rng.normal(size=3).astype(np.float32)
    return loc, C, x


def test_mvn_log_prob_three_parameterizations(mvn_setup):
    loc, C, x = mvn_setup
    ref = _mvn_ref_logpdf(x, loc, C)
    L = np.linalg.cholesky(C).astype(np.float32)
    P = np.linalg.inv(C).astype(np.float32)
    for kw in (dict(covariance_matrix=paddle.to_tensor(C)),
               dict(scale_tril=paddle.to_tensor(L)),
               dict(precision_matrix=paddle.to_tensor(P))):
        d = MultivariateNormal(paddle.to_tensor(loc), **kw)
        lp = float(d.log_prob(paddle.to_tensor(x)).numpy())
        np.testing.assert_allclose(lp, ref, rtol=5e-3)
    with pytest.raises(ValueError, match="Exactly one"):
        MultivariateNormal(paddle.to_tensor(loc))


def test_mvn_entropy_and_moments(mvn_setup):
    loc, C, _ = mvn_setup
    d = MultivariateNormal(paddle.to_tensor(loc),
                           covariance_matrix=paddle.to_tensor(C))
    k = 3
    ref_ent = 0.5 * (k * (1 + np.log(2 * np.pi))
                     + np.log(np.linalg.det(C)))
    np.testing.assert_allclose(float(d.entropy().numpy()), ref_ent,
                               rtol=1e-4)
    np.testing.assert_allclose(d.mean.numpy(), loc, rtol=1e-6)
    np.testing.assert_allclose(d.variance.numpy(), np.diag(C), rtol=1e-4)
    paddle.seed(0)
    s = d.sample((5000,)).numpy()
    assert s.shape == (5000, 3)
    np.testing.assert_allclose(s.mean(0), loc, atol=0.15)


def test_mvn_kl(mvn_setup):
    loc, C, _ = mvn_setup
    p = MultivariateNormal(paddle.to_tensor(loc),
                           covariance_matrix=paddle.to_tensor(C))
    q = MultivariateNormal(paddle.to_tensor(loc + 0.5),
                           covariance_matrix=paddle.to_tensor(C * 1.5))
    assert abs(float(kl_divergence(p, p).numpy())) < 1e-6
    # closed form vs definition: for MVNs KL = 0.5*(tr + m - k + logdet)
    d = 0.5 * np.ones(3, np.float32)
    tr = np.trace(np.linalg.solve(1.5 * C, C))
    m = d @ np.linalg.solve(1.5 * C, d)
    logdet = np.log(np.linalg.det(1.5 * C) / np.linalg.det(C))
    ref = 0.5 * (tr + m - 3 + logdet)
    np.testing.assert_allclose(float(kl_divergence(p, q).numpy()), ref,
                               rtol=1e-3)


@pytest.mark.parametrize("pr", [0.2, 0.4999, 0.5, 0.77])
def test_continuous_bernoulli_density_normalizes(pr):
    cb = ContinuousBernoulli(paddle.to_tensor(np.float32(pr)))
    xs = np.linspace(1e-6, 1 - 1e-6, 20001, dtype=np.float32)
    pdf = np.exp(cb.log_prob(paddle.to_tensor(xs)).numpy())
    Z = np.trapezoid(pdf, xs)
    mean_num = np.trapezoid(pdf * xs, xs)
    var_num = np.trapezoid(pdf * (xs - mean_num) ** 2, xs)
    np.testing.assert_allclose(Z, 1.0, atol=1e-3)
    np.testing.assert_allclose(float(cb.mean.numpy()[0]), mean_num,
                               atol=1e-3)
    np.testing.assert_allclose(float(cb.variance.numpy()[0]), var_num,
                               atol=1e-3)


def test_continuous_bernoulli_cdf_icdf_sample():
    cb = ContinuousBernoulli(paddle.to_tensor(np.float32(0.3)))
    u = np.array([0.1, 0.5, 0.9], np.float32)
    x = cb._icdf(u)
    np.testing.assert_allclose(
        cb.cdf(paddle.to_tensor(np.asarray(x))).numpy(), u, atol=1e-4)
    paddle.seed(0)
    s = cb.sample((4000,)).numpy()
    assert ((s >= 0) & (s <= 1)).all()
    np.testing.assert_allclose(s.mean(), float(cb.mean.numpy()[0]),
                               atol=0.02)
    q = ContinuousBernoulli(paddle.to_tensor(np.float32(0.6)))
    assert float(kl_divergence(cb, cb).numpy()[0]) == pytest.approx(
        0.0, abs=1e-6)
    assert float(kl_divergence(cb, q).numpy()[0]) > 0


def test_independent_reinterprets_batch_dims():
    base = Normal(paddle.to_tensor(np.zeros((2, 3), np.float32)),
                  paddle.to_tensor(np.ones((2, 3), np.float32)))
    ind = Independent(base, 1)
    assert tuple(ind.batch_shape) == (2,)
    assert tuple(ind.event_shape) == (3,)
    v = np.random.default_rng(0).normal(size=(2, 3)).astype(np.float32)
    lp = ind.log_prob(paddle.to_tensor(v)).numpy()
    assert lp.shape == (2,)
    np.testing.assert_allclose(
        lp, base.log_prob(paddle.to_tensor(v)).numpy().sum(-1),
        rtol=1e-5)
    np.testing.assert_allclose(
        ind.entropy().numpy(), base.entropy().numpy().sum(-1), rtol=1e-5)
    base2 = Normal(paddle.to_tensor(np.ones((2, 3), np.float32)),
                   paddle.to_tensor(np.ones((2, 3), np.float32)))
    kl = kl_divergence(Independent(base, 1), Independent(base2, 1))
    np.testing.assert_allclose(
        kl.numpy(), kl_divergence(base, base2).numpy().sum(-1),
        rtol=1e-5)
    with pytest.raises(ValueError):
        Independent(base, 3)


def test_exponential_family_entropy_bregman():
    # Exponential(rate): eta = -rate, A(eta) = -log(-eta), carrier = 0;
    # H = 1 - log(rate) — check the generic Bregman entropy against it
    import jax.numpy as jnp

    class ExpFam(ExponentialFamily):
        def __init__(self, rate):
            self.rate = np.float32(rate)
            super().__init__((), ())

        @property
        def _natural_parameters(self):
            return (paddle.to_tensor(-self.rate),)

        def _log_normalizer(self, eta):
            return -jnp.log(-eta)

        @property
        def _mean_carrier_measure(self):
            return 0.0

    d = ExpFam(2.0)
    np.testing.assert_allclose(float(d.entropy().numpy()),
                               1.0 - np.log(2.0), rtol=1e-5)

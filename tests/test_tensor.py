"""Tensor façade basics. Mirrors the reference's eager tensor tests
(test/legacy_test/test_eager_tensor.py style, SURVEY §4)."""
import numpy as np
import pytest

import paddle_tpu as pp


def test_to_tensor_roundtrip():
    x = pp.to_tensor([[1.0, 2.0], [3.0, 4.0]])
    assert x.shape == [2, 2]
    assert x.dtype == np.dtype("float32")
    np.testing.assert_allclose(x.numpy(), [[1, 2], [3, 4]])


def test_default_dtype_float64_input():
    x = pp.to_tensor(np.array(1.5))  # np float64 stays float64 (explicit array)
    y = pp.to_tensor(1.5)            # python float -> default dtype
    assert y.dtype == np.dtype("float32")


def test_dtype_cast():
    x = pp.to_tensor([1, 2, 3])
    assert x.dtype == np.dtype("int32") or x.dtype == np.dtype("int64")
    y = x.astype("float32")
    assert y.dtype == np.dtype("float32")
    z = x.cast("bfloat16")
    assert z.dtype.itemsize == 2


def test_item_and_len():
    x = pp.to_tensor([[1.0, 2.0]])
    assert len(x) == 1
    assert pp.to_tensor(3.5).item() == pytest.approx(3.5)


def test_operators():
    a = pp.to_tensor([1.0, 2.0])
    b = pp.to_tensor([3.0, 4.0])
    np.testing.assert_allclose((a + b).numpy(), [4, 6])
    np.testing.assert_allclose((a - b).numpy(), [-2, -2])
    np.testing.assert_allclose((a * b).numpy(), [3, 8])
    np.testing.assert_allclose((b / a).numpy(), [3, 2])
    np.testing.assert_allclose((a ** 2).numpy(), [1, 4])
    np.testing.assert_allclose((2.0 * a).numpy(), [2, 4])
    np.testing.assert_allclose((1.0 - a).numpy(), [0, -1])
    np.testing.assert_allclose((-a).numpy(), [-1, -2])
    assert bool((a == a).all())
    assert bool((a < b).all())


def test_matmul_shapes():
    a = pp.ones([2, 3])
    b = pp.ones([3, 4])
    assert (a @ b).shape == [2, 4]
    c = pp.ones([5, 2, 3])
    assert pp.matmul(c, b).shape == [5, 2, 4]
    assert pp.matmul(a, a, transpose_y=True).shape == [2, 2]


def test_getitem_setitem():
    x = pp.to_tensor(np.arange(12).reshape(3, 4).astype("float32"))
    np.testing.assert_allclose(x[1].numpy(), [4, 5, 6, 7])
    np.testing.assert_allclose(x[:, 1].numpy(), [1, 5, 9])
    np.testing.assert_allclose(x[1:, ::2].numpy(), [[4, 6], [8, 10]])
    idx = pp.to_tensor([0, 2])
    np.testing.assert_allclose(x[idx].numpy(), [[0, 1, 2, 3], [8, 9, 10, 11]])
    x[0, 0] = 42.0
    assert x.numpy()[0, 0] == 42.0
    x[:, 1] = pp.to_tensor([7.0, 7.0, 7.0])
    np.testing.assert_allclose(x.numpy()[:, 1], [7, 7, 7])


def test_bool_mask_getitem():
    x = pp.to_tensor([1.0, -2.0, 3.0])
    m = x > pp.to_tensor(0.0)
    np.testing.assert_allclose(x[m].numpy(), [1, 3])


def test_reshape_family():
    x = pp.arange(24, dtype="float32")
    assert x.reshape([2, 3, 4]).shape == [2, 3, 4]
    assert x.reshape([2, -1]).shape == [2, 12]
    assert x.reshape([2, 3, 4]).flatten(1, 2).shape == [2, 12]
    assert x.reshape([1, 24, 1]).squeeze().shape == [24]
    assert x.unsqueeze(0).shape == [1, 24]
    assert x.reshape([2, 3, 4]).transpose([2, 0, 1]).shape == [4, 2, 3]


def test_concat_split_stack():
    a = pp.ones([2, 3])
    b = pp.zeros([2, 3])
    c = pp.concat([a, b], axis=0)
    assert c.shape == [4, 3]
    s = pp.split(c, 2, axis=0)
    assert len(s) == 2 and s[0].shape == [2, 3]
    s2 = pp.split(c, [1, 3], axis=0)
    assert s2[1].shape == [3, 3]
    st = pp.stack([a, b], axis=1)
    assert st.shape == [2, 2, 3]


def test_reductions():
    x = pp.to_tensor([[1.0, 2.0], [3.0, 4.0]])
    assert x.sum().item() == 10
    assert x.mean().item() == 2.5
    assert x.max().item() == 4
    np.testing.assert_allclose(x.sum(axis=0).numpy(), [4, 6])
    np.testing.assert_allclose(x.sum(axis=1, keepdim=True).numpy(), [[3], [7]])
    assert x.argmax().item() == 3
    np.testing.assert_allclose(x.argmax(axis=1).numpy(), [1, 1])
    assert x.prod().item() == 24


def test_where_clip_topk():
    x = pp.to_tensor([3.0, 1.0, 2.0])
    v, i = pp.topk(x, 2)
    np.testing.assert_allclose(v.numpy(), [3, 2])
    np.testing.assert_allclose(i.numpy(), [0, 2])
    np.testing.assert_allclose(pp.clip(x, 1.5, 2.5).numpy(), [2.5, 1.5, 2.0])
    c = pp.where(x > pp.to_tensor(1.5), x, pp.zeros_like(x))
    np.testing.assert_allclose(c.numpy(), [3, 0, 2])


def test_gather_scatter():
    x = pp.to_tensor(np.arange(12).reshape(3, 4).astype("float32"))
    g = pp.gather(x, pp.to_tensor([2, 0]), axis=0)
    np.testing.assert_allclose(g.numpy(), [[8, 9, 10, 11], [0, 1, 2, 3]])
    idx = pp.to_tensor([[0, 1], [2, 3]])
    np.testing.assert_allclose(
        pp.gather_nd(x, idx).numpy(), [1, 11])
    t = pp.take_along_axis(x, pp.to_tensor([[0], [1], [2]]), axis=1)
    np.testing.assert_allclose(t.numpy(), [[0], [5], [10]])


def test_creation_ops():
    assert pp.zeros([2, 2]).sum().item() == 0
    assert pp.ones([2, 2], dtype="int32").dtype == np.dtype("int32")
    assert pp.full([2], 7).numpy().tolist() == [7, 7]
    np.testing.assert_allclose(pp.arange(5).numpy(), [0, 1, 2, 3, 4])
    np.testing.assert_allclose(pp.eye(2).numpy(), [[1, 0], [0, 1]])
    np.testing.assert_allclose(pp.tril(pp.ones([2, 2])).numpy(), [[1, 0], [1, 1]])
    assert pp.linspace(0, 1, 5).shape == [5]
    x = pp.one_hot(pp.to_tensor([0, 2]), 3)
    np.testing.assert_allclose(x.numpy(), [[1, 0, 0], [0, 0, 1]])


def test_random_reproducible():
    pp.seed(42)
    a = pp.randn([4])
    pp.seed(42)
    b = pp.randn([4])
    np.testing.assert_allclose(a.numpy(), b.numpy())
    u = pp.uniform([1000], min=0.0, max=1.0)
    assert 0.0 <= float(u.min()) and float(u.max()) <= 1.0
    r = pp.randperm(10)
    assert sorted(r.tolist()) == list(range(10))


def test_save_load(tmp_path):
    x = pp.to_tensor([[1.0, 2.0]])
    state = {"w": x, "step": 3, "nested": {"b": pp.ones([2])}}
    p = str(tmp_path / "ckpt.pd")
    pp.save(state, p)
    loaded = pp.load(p)
    np.testing.assert_allclose(loaded["w"].numpy(), x.numpy())
    assert loaded["step"] == 3
    np.testing.assert_allclose(loaded["nested"]["b"].numpy(), [1, 1])


def test_einsum_and_linalg():
    a = pp.ones([2, 3])
    b = pp.ones([3, 4])
    e = pp.einsum("ij,jk->ik", a, b)
    np.testing.assert_allclose(e.numpy(), 3 * np.ones((2, 4)))
    m = pp.to_tensor([[2.0, 0.0], [0.0, 2.0]])
    np.testing.assert_allclose(pp.inverse(m).numpy(), [[0.5, 0], [0, 0.5]])
    assert pp.det(m).item() == pytest.approx(4.0)
    assert pp.norm(pp.to_tensor([3.0, 4.0])).item() == pytest.approx(5.0)


def test_flags():
    pp.set_flags({"check_nan_inf": True})
    try:
        with pytest.raises(FloatingPointError):
            _ = pp.log(pp.to_tensor([-1.0]))
    finally:
        pp.set_flags({"check_nan_inf": False})


def test_tensor_array_ops():
    """TensorArray (SURVEY C8): create/write/read/length semantics."""
    arr = pp.create_array()
    pp.array_write(pp.to_tensor([1.0]), 0, arr)
    pp.array_write(pp.to_tensor([2.0]), 1, arr)
    pp.array_write(pp.to_tensor([9.0]), 0, arr)  # overwrite
    assert pp.array_length(arr) == 2
    assert float(np.asarray(pp.array_read(arr, 0)._read())[0]) == 9.0
    assert float(np.asarray(pp.array_read(arr, 1)._read())[0]) == 2.0
    with pytest.raises(IndexError):
        pp.array_read(arr, 5)
    with pytest.raises(IndexError):
        pp.array_write(pp.to_tensor([0.0]), 7, arr)
    init = pp.create_array(initialized_list=[np.zeros(2, "float32")])
    assert pp.array_length(init) == 1

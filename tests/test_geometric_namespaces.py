"""paddle.geometric + small compat namespaces (hub/reader/dataset/
sysconfig/tensor/base). Reference: python/paddle/geometric/, hapi/hub.py,
reader/decorator.py."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle


# --- geometric math (reference geometric/math.py docstring examples) ----

def test_segment_sum_mean_min_max():
    data = paddle.to_tensor(
        [[1., 2., 3.], [3., 2., 1.], [4., 5., 6.]], dtype="float32")
    ids = paddle.to_tensor([0, 0, 1], dtype="int32")
    np.testing.assert_allclose(
        paddle.geometric.segment_sum(data, ids).numpy(),
        [[4., 4., 4.], [4., 5., 6.]])
    np.testing.assert_allclose(
        paddle.geometric.segment_mean(data, ids).numpy(),
        [[2., 2., 2.], [4., 5., 6.]])
    np.testing.assert_allclose(
        paddle.geometric.segment_min(data, ids).numpy(),
        [[1., 2., 1.], [4., 5., 6.]])
    np.testing.assert_allclose(
        paddle.geometric.segment_max(data, ids).numpy(),
        [[3., 2., 3.], [4., 5., 6.]])


def test_segment_sum_grad():
    data = paddle.to_tensor([[1., 2.], [3., 4.], [5., 6.]])
    data.stop_gradient = False
    ids = paddle.to_tensor([0, 0, 1], dtype="int32")
    out = paddle.geometric.segment_sum(data, ids)
    out.sum().backward()
    np.testing.assert_allclose(data.grad.numpy(), np.ones((3, 2)))


# --- message passing (reference send_recv.py docstring example) ---------

def test_send_u_recv():
    x = paddle.to_tensor([[0, 2, 3], [1, 4, 5], [2, 6, 7]], dtype="float32")
    src = paddle.to_tensor([0, 1, 2, 0], dtype="int32")
    dst = paddle.to_tensor([1, 2, 1, 0], dtype="int32")
    out = paddle.geometric.send_u_recv(x, src, dst, reduce_op="sum")
    np.testing.assert_allclose(
        out.numpy(), [[0, 2, 3], [2, 8, 10], [1, 4, 5]])
    out = paddle.geometric.send_u_recv(x, src, dst, reduce_op="mean")
    np.testing.assert_allclose(
        out.numpy(), [[0, 2, 3], [1, 4, 5], [1, 4, 5]])


def test_send_u_recv_out_size_and_default_rows():
    x = paddle.to_tensor([[0, 2, 3], [1, 4, 5], [2, 6, 7]], dtype="float32")
    src = paddle.to_tensor([0, 2, 0], dtype="int32")
    dst = paddle.to_tensor([1, 1, 0], dtype="int32")
    out = paddle.geometric.send_u_recv(x, src, dst, out_size=2)
    assert out.shape[0] == 2
    out = paddle.geometric.send_u_recv(x, src, dst)
    np.testing.assert_allclose(out.numpy()[2], [0, 0, 0])


def test_send_ue_recv_and_send_uv():
    x = paddle.to_tensor([[0., 2., 3.], [1., 4., 5.], [2., 6., 7.]])
    y = paddle.to_tensor([1., 1., 1., 1.])
    src = paddle.to_tensor([0, 1, 2, 0], dtype="int32")
    dst = paddle.to_tensor([1, 2, 1, 0], dtype="int32")
    out = paddle.geometric.send_ue_recv(
        x, y.reshape([4, 1]), src, dst, message_op="add", reduce_op="sum")
    np.testing.assert_allclose(
        out.numpy(), [[1, 3, 4], [4, 10, 12], [2, 5, 6]])
    uv = paddle.geometric.send_uv(x, x, src, dst, message_op="mul")
    np.testing.assert_allclose(uv.numpy()[0], (x.numpy()[0] * x.numpy()[1]))


# --- reindex + sampling (reference reindex.py docstring example) --------

def test_reindex_graph():
    x = paddle.to_tensor([0, 1, 2], dtype="int64")
    neighbors = paddle.to_tensor([8, 9, 0, 4, 7, 6, 7], dtype="int64")
    count = paddle.to_tensor([2, 3, 2], dtype="int32")
    src, dst, nodes = paddle.geometric.reindex_graph(x, neighbors, count)
    np.testing.assert_array_equal(src.numpy(), [3, 4, 0, 5, 6, 7, 6])
    np.testing.assert_array_equal(dst.numpy(), [0, 0, 1, 1, 1, 2, 2])
    np.testing.assert_array_equal(nodes.numpy(), [0, 1, 2, 8, 9, 4, 7, 6])


def test_reindex_heter_graph():
    x = paddle.to_tensor([0, 1, 2], dtype="int64")
    n1 = paddle.to_tensor([8, 9, 0, 4, 7, 6, 7], dtype="int64")
    c1 = paddle.to_tensor([2, 3, 2], dtype="int32")
    n2 = paddle.to_tensor([0, 2, 3], dtype="int64")
    c2 = paddle.to_tensor([1, 1, 1], dtype="int32")
    src, dst, nodes = paddle.geometric.reindex_heter_graph(
        x, [n1, n2], [c1, c2])
    assert len(src.numpy()) == 10 and len(dst.numpy()) == 10
    np.testing.assert_array_equal(nodes.numpy()[:3], [0, 1, 2])


def test_sample_neighbors():
    # CSC: node 0 -> [1, 2], node 1 -> [0], node 2 -> [0, 1]
    row = paddle.to_tensor([1, 2, 0, 0, 1], dtype="int64")
    colptr = paddle.to_tensor([0, 2, 3, 5], dtype="int64")
    nodes = paddle.to_tensor([0, 2], dtype="int64")
    paddle.seed(7)
    neigh, cnt = paddle.geometric.sample_neighbors(row, colptr, nodes,
                                                   sample_size=1)
    assert cnt.numpy().tolist() == [1, 1]
    assert len(neigh.numpy()) == 2
    neigh, cnt = paddle.geometric.sample_neighbors(row, colptr, nodes)
    assert cnt.numpy().tolist() == [2, 2]
    w = paddle.to_tensor([0.9, 0.1, 1.0, 0.5, 0.5], dtype="float32")
    neigh, cnt, eids = paddle.geometric.weighted_sample_neighbors(
        row, colptr, w, nodes, sample_size=2,
        eids=paddle.to_tensor([0, 1, 2, 3, 4], dtype="int64"),
        return_eids=True)
    assert len(neigh.numpy()) == int(cnt.numpy().sum())
    assert len(eids.numpy()) == len(neigh.numpy())


# --- small namespaces ---------------------------------------------------

def test_hub_local(tmp_path):
    (tmp_path / "hubconf.py").write_text(
        "dependencies = ['numpy']\n"
        "def tiny(k=3):\n"
        "    '''a tiny entry'''\n"
        "    return k * 2\n")
    assert "tiny" in paddle.hub.list(str(tmp_path), source="local")
    assert "tiny entry" in paddle.hub.help(str(tmp_path), "tiny",
                                           source="local")
    assert paddle.hub.load(str(tmp_path), "tiny", source="local", k=5) == 10
    with pytest.raises(RuntimeError):
        paddle.hub.load(str(tmp_path), "missing", source="local")
    with pytest.raises(RuntimeError):
        paddle.hub.list("owner/repo", source="github")


def test_reader_decorators():
    def r():
        yield from range(10)

    assert list(paddle.reader.firstn(r, 4)()) == [0, 1, 2, 3]
    assert list(paddle.reader.cache(r)()) == list(range(10))
    assert sorted(paddle.reader.shuffle(r, 5)()) == list(range(10))
    assert list(paddle.reader.chain(r, r)()) == list(range(10)) * 2
    m = paddle.reader.map_readers(lambda a, b: a + b, r, r)
    assert list(m()) == [2 * i for i in range(10)]
    assert list(paddle.reader.buffered(r, 3)()) == list(range(10))
    x = paddle.reader.xmap_readers(lambda v: v * v, r, 3, 4, order=True)
    assert list(x()) == [i * i for i in range(10)]
    c = paddle.reader.compose(r, r)
    assert list(c())[0] == (0, 0)
    mp = paddle.reader.multiprocess_reader([r, r])
    assert sorted(mp()) == sorted(list(range(10)) * 2)


def test_sysconfig_and_namespaces():
    assert isinstance(paddle.sysconfig.get_include(), str)
    assert os.path.isdir(paddle.sysconfig.get_lib())
    assert paddle.tensor.concat is not None
    assert paddle.base.Program is paddle.static.Program
    prog = paddle.static.Program()
    with paddle.static.program_guard(prog):
        assert paddle.static.default_main_program() is prog
    with pytest.raises(NotImplementedError):
        paddle.onnx.export(None, "x")


def test_dataset_common_gating(tmp_path):
    f = tmp_path / "blob.bin"
    f.write_bytes(b"hello")
    md5 = paddle.dataset.common.md5file(str(f))
    assert len(md5) == 32
    with pytest.raises(FileNotFoundError):
        paddle.dataset.common.download("http://x/y.gz", "nope", "0" * 32)
    with pytest.raises((FileNotFoundError, RuntimeError)):
        next(paddle.dataset.mnist.train()())


def test_cost_model():
    import paddle_tpu.cost_model as cm
    m = cm.CostModel()
    cost = m.profile_measure(lambda a, b: a @ b,
                             (np.ones((64, 64), "float32"),
                              np.ones((64, 64), "float32")))
    assert cost["flops"] > 0 and cost["measured_seconds"] > 0
    t = m.get_static_op_time("tanh")
    assert t["time"] > 0 and m.static_cost_data()


def test_ps_datasets(tmp_path):
    import paddle_tpu.distributed as dist
    f1 = tmp_path / "a.txt"
    f1.write_text("\n".join(f"{i} {i*2}" for i in range(10)) + "\n")
    parse = lambda ln: tuple(int(v) for v in ln.split())

    ds = dist.InMemoryDataset()
    ds.init(batch_size=4, parse_fn=parse)
    ds.set_filelist([str(f1)])
    ds.load_into_memory()
    assert ds.get_memory_data_size() == 10
    ds.local_shuffle(seed=0)
    batches = list(ds)
    assert len(batches) == 3 and sorted(
        s for b in batches for s in b) == [(i, 2 * i) for i in range(10)]
    ds.release_memory()

    qs = dist.QueueDataset()
    qs.init(batch_size=5, parse_fn=parse)
    qs.set_filelist([str(f1)])
    assert sum(len(b) for b in qs) == 10

"""Quantized serving path (ISSUE 7).

Correctness model, layered:

* the int8 ragged attention kernel and the fused weight-only matmul are
  bitwise against their jnp twins in interpret mode (kernel-level tests
  in ``tests/test_pallas.py`` / ``tests/test_quantization.py``);
* the QUANT ENGINE's greedy token streams are IDENTICAL to the fp
  engine / ``generate()`` on the tiny-model serving workloads (int8
  absmax per-vector error does not flip tiny-model argmax — asserted,
  not assumed);
* the prefix-cache drills (COW, eviction, preempt-requeue restore) and
  the pool-conservation audit re-run unchanged with
  ``serving_kv_quant=on`` — scale side-pools ride the same block
  tables, so the scheduling layer never special-cases them;
* with the flag off the engine is the fp path bitwise (same pools, same
  programs, same bytes — pinned against ``generate(kv_cache='paged')``).

The workloads deliberately REPLAY test_serving_engine.py's fp drills
(same rng seeds, prompts, geometries) on the session-shared tiny model:
the fp reference programs are already compiled, so the quant suite pays
only for its own quant-geometry programs.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference import ContinuousBatchingEngine
from paddle_tpu.models import generate
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM


@pytest.fixture(scope="module")
def gpt(serving_gpt):
    return serving_gpt     # session tiny model (tests/conftest.py)


def _refs(model, prompts, new, kv="dense"):
    return [generate(model, p[None, :], max_new_tokens=n,
                     kv_cache=kv).numpy()[0]
            for p, n in zip(prompts, new)]


def _engine(model, **kw):
    args = dict(max_slots=2, page_size=4, max_seq_len=32,
                decode_window=4, prefill_chunk=8, q_block=2)
    args.update(kw)
    return ContinuousBatchingEngine(model, **args)


def _assert_conserved(eng):
    st = eng.stats
    assert st["pages_in_use"] == 0
    assert (st["pages_free"] + st["cached_pages"]
            == eng.total_pages - 1)
    eng._cache.check()


# ----------------------------------------------------------------------
# token parity + byte accounting
# ----------------------------------------------------------------------

def test_quant_engine_tokens_match_fp_gpt(gpt):
    """The slot-contention workload through the int8-KV engine: every
    greedy stream equals the fp generate() reference token for token,
    and the mixed (chunked prefill) + windowed decode paths both ran."""
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, 96, (n,)).astype(np.int32)
               for n in (5, 9, 3, 12)]
    new = [6, 4, 7, 5]
    refs = _refs(gpt, prompts, new)
    eng = _engine(gpt, kv_quant=True)
    rids = [eng.add_request(p, n) for p, n in zip(prompts, new)]
    done = eng.run()
    for rid, ref in zip(rids, refs):
        np.testing.assert_array_equal(done[rid].sequence, ref)
    assert eng.stats["kv_quant"] is True
    assert eng.stats["mixed_steps"] >= 2
    assert eng.stats["decode_dispatches"] >= 1
    _assert_conserved(eng)
    # int8 data pools + f32 scale side-pools actually installed
    cfg = gpt.cfg
    assert len(eng._caches) == 4 * cfg.num_layers
    assert str(eng._caches[0].dtype).endswith("int8")
    assert str(eng._caches[2 * cfg.num_layers].dtype).endswith("float32")


def test_quant_engine_tokens_match_fp_llama_gqa():
    paddle.seed(0)
    m = LlamaForCausalLM(LlamaConfig(
        vocab_size=96, hidden_size=32, num_layers=2, num_heads=4,
        num_kv_heads=2, max_seq_len=64))
    m.eval()
    rng = np.random.default_rng(3)
    prompts = [rng.integers(0, 96, (n,)).astype(np.int32)
               for n in (7, 4, 11)]
    new = [5, 6, 4]
    refs = [generate(m, p[None, :], max_new_tokens=n).numpy()[0]
            for p, n in zip(prompts, new)]
    eng = ContinuousBatchingEngine(m, max_slots=2, page_size=8,
                                   max_seq_len=32, decode_window=3,
                                   prefill_chunk=6, q_block=2,
                                   pages_per_block=1, kv_quant=True)
    rids = [eng.add_request(p, n) for p, n in zip(prompts, new)]
    done = eng.run()
    for rid, ref in zip(rids, refs):
        np.testing.assert_array_equal(done[rid].sequence, ref)


def test_quant_kv_bytes_per_sequence_halved(gpt):
    """The acceptance gate: KV pool bytes per resident sequence drop
    below HALF of fp32 — pages hold the same token counts, so byte
    accounting per page is the per-sequence claim.  Exact layout:
    D*1 (int8) + 4 (f32 scale) per (head, slot) vs D*4 fp32.
    Construction-only (no dispatch): the gauges are static geometry."""
    cfg = gpt.cfg
    fp = _engine(gpt).stats
    q = _engine(gpt, kv_quant=True).stats
    assert q["kv_page_bytes"] * 2 <= fp["kv_page_bytes"]
    d = cfg.head_dim
    assert q["kv_page_bytes"] == fp["kv_page_bytes"] * (d + 4) // (4 * d)
    assert q["kv_bytes_in_use"] == 0 and fp["kv_bytes_in_use"] == 0


def test_quant_flag_off_restores_fp_engine_bitwise(gpt):
    """``serving_kv_quant`` off (the default): fp32 pools, 2L cache
    list, outputs bitwise-equal to generate(kv_cache='paged') — the
    refactored code path with quant disabled IS the old fp path.  (The
    whole fp serving suite, test_serving_engine.py, runs flag-off too;
    this pins the flag/kwarg plumbing itself.)"""
    from paddle_tpu.core import state

    rng = np.random.default_rng(7)
    prompts = [rng.integers(0, 96, (n,)).astype(np.int32)
               for n in (6, 8, 5, 7)]
    new = [8, 7, 8, 6]
    refs = _refs(gpt, prompts, new, kv="paged")
    assert state.get_flag("serving_kv_quant") is False  # default off
    eng = _engine(gpt)                      # flag-driven: fp
    assert eng.kv_quant is False
    assert len(eng._caches) == 2 * gpt.cfg.num_layers
    assert str(eng._caches[0].dtype).endswith("float32")
    rids = [eng.add_request(p, n) for p, n in zip(prompts, new)]
    done = eng.run()
    for rid, ref in zip(rids, refs):
        np.testing.assert_array_equal(done[rid].sequence, ref)
    # flag flips the default; kwarg spellings parse like prefix_cache's
    state.set_flags({"serving_kv_quant": True})
    try:
        assert _engine(gpt).kv_quant is True
        assert _engine(gpt, kv_quant="off").kv_quant is False
    finally:
        state.set_flags({"serving_kv_quant": False})
    assert _engine(gpt, kv_quant="on").kv_quant is True
    # strict parse: lossy quantization must never engage on a typo
    with pytest.raises(ValueError, match="kv_quant"):
        _engine(gpt, kv_quant="disabled")


# ----------------------------------------------------------------------
# prefix-cache drills under quant
# ----------------------------------------------------------------------

def test_quant_prefix_cache_shared_and_cow(gpt):
    """Shared-prefix reuse AND the copy-on-write full-hit path with
    int8 pages: scale side-pools travel with the matched/copied pages
    (same block tables, same COW dispatch), so hits stay
    token-identical and exactly one token recomputes on a full hit."""
    rng = np.random.default_rng(29)
    shared = rng.integers(0, 96, (12,)).astype(np.int32)  # 3 full pages
    tails = [rng.integers(0, 96, (n,)).astype(np.int32)
             for n in (3, 2, 5, 1)]
    prompts = [np.concatenate([shared, t]) for t in tails]
    new = [6, 5, 4, 6]
    refs = _refs(gpt, prompts, new, kv="paged")
    eng = _engine(gpt, kv_quant=True)
    rids = [eng.add_request(p, n) for p, n in zip(prompts, new)]
    done = eng.run()
    for rid, ref in zip(rids, refs):
        np.testing.assert_array_equal(done[rid].sequence, ref)
    st = eng.stats
    assert st["cache_hits"] >= 2   # later admissions rode shared pages
    assert st["prefill_tokens_computed"] < st["prefill_tokens_requested"]
    _assert_conserved(eng)

    # COW: full page-aligned hit recomputes exactly one token
    prompt = rng.integers(0, 96, (8,)).astype(np.int32)   # 2 full pages
    (ref,) = _refs(gpt, [prompt], [6], kv="paged")
    eng = _engine(gpt, kv_quant=True)
    r1 = eng.add_request(prompt, 6)
    np.testing.assert_array_equal(eng.run()[r1].sequence, ref)
    base = eng.stats["prefill_tokens_computed"]
    r2 = eng.add_request(prompt, 6)
    np.testing.assert_array_equal(eng.run()[r2].sequence, ref)
    assert eng.stats["prefill_tokens_computed"] - base == 1
    _assert_conserved(eng)


def test_quant_preempt_requeue_and_evict_drills(gpt):
    """The forced-preemption and forced-eviction drills with int8
    pages: victims republish and restore, evicted prefixes re-prefill,
    every stream token-identical to the fp reference.  (The drills
    replay test_engine_preempt_requeue_recompute_drop /
    test_engine_cache_evict_drill_bitwise on the shared engine
    geometry, so only the quant programs compile fresh; the truly
    starved-pool preemption path is the same allocator code, drilled fp
    in test_engine_preempt_requeue_bitwise.)"""
    from paddle_tpu.resilience import faults

    rng = np.random.default_rng(41)
    p1 = rng.integers(0, 96, (6,)).astype(np.int32)
    p2 = rng.integers(0, 96, (7,)).astype(np.int32)
    refs = _refs(gpt, [p1, p2], [8, 8], kv="paged")
    faults.clear()
    try:
        eng = _engine(gpt, kv_quant=True)
        r1 = eng.add_request(p1, 8)
        r2 = eng.add_request(p2, 8)
        # r1's growth hits injected pressure -> r2 (latest) preempts
        faults.inject("engine_page_pressure", match=str(r1))
        done = eng.run()
        np.testing.assert_array_equal(done[r1].sequence, refs[0])
        np.testing.assert_array_equal(done[r2].sequence, refs[1])
        assert eng.stats["preemptions"] >= 1
        assert eng.stats["cache_hits"] >= 1   # victim restored from its
        _assert_conserved(eng)                # own published int8 pages
    finally:
        faults.clear()

    # forced eviction: cached int8 prefix pages reclaimed, re-admission
    # of the evicted prefix re-prefills bitwise
    rng = np.random.default_rng(37)
    p1 = rng.integers(0, 96, (9,)).astype(np.int32)
    (ref1,) = _refs(gpt, [p1], [6], kv="paged")
    faults.clear()
    try:
        eng = _engine(gpt, kv_quant=True)
        r1 = eng.add_request(p1, 6)
        np.testing.assert_array_equal(eng.run()[r1].sequence, ref1)
        assert eng.stats["cached_pages"] >= 2
        faults.inject("engine_cache_evict", times=0)
        r2 = eng.add_request(p1, 6)
        done = eng.run()
        faults.clear()
        np.testing.assert_array_equal(done[r2].sequence, ref1)
        assert eng.stats["evictions"] >= 1
        _assert_conserved(eng)
    finally:
        faults.clear()


# ----------------------------------------------------------------------
# weight-only generation path + bench accounting smokes
# ----------------------------------------------------------------------

@pytest.fixture(scope="module")
def gpt_q(gpt):
    """Weight-only int8 twin of the session tiny model (same seed +
    config rebuilds identical fp weights before the swap)."""
    from paddle_tpu.quantization import weight_only_quantize

    paddle.seed(0)
    mq = weight_only_quantize(type(gpt)(gpt.cfg))
    mq.eval()
    return mq


def test_weight_only_model_generate(gpt, gpt_q):
    """``weight_only_quantize`` swaps every Linear for the fused int8
    path; generate() serves the swapped model with token streams equal
    to the fp model's (tiny-model argmax is int8-weight stable —
    asserted).  Dense and paged decode both route every projection
    through the fused kernel's jnp twin on CPU."""
    from paddle_tpu.quantization import WeightOnlyLinear

    assert isinstance(gpt_q.gpt.blocks[0].attn.qkv, WeightOnlyLinear)
    assert str(gpt_q.gpt.blocks[0].attn.qkv.qweight.dtype
               ).endswith("int8")
    rng = np.random.default_rng(11)
    prompts = [rng.integers(0, 96, (7,)).astype(np.int32)
               for _ in range(2)]
    refs = _refs(gpt, prompts, [6, 6], kv="paged")
    for p, ref in zip(prompts, refs):
        out = generate(gpt_q, p[None, :], max_new_tokens=6,
                       kv_cache="paged").numpy()[0]
        np.testing.assert_array_equal(out, ref)


def test_serving_bench_quant_rows_accounting(gpt, gpt_q):
    """CPU tiny-model smoke for the ``quant_b8`` / ``weight_only_b1``
    bench rows: quantized rooflines strictly below the fp twins, KV
    bytes at most half, outputs token-equal, zero leaked pages."""
    import importlib.util
    import os

    path = os.path.join(os.path.dirname(__file__), os.pardir,
                        "benchmarks", "serving_bench.py")
    spec = importlib.util.spec_from_file_location(
        "serving_bench_quant_smoke", path)
    sb = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(sb)
    # geometry mirrors _engine() so the engine programs compiled by the
    # parity tests above are reused
    row = sb._measure_quant(gpt.cfg, gpt, gbps=819.0, slots=2,
                            prompt_len=9, new_tokens=4, page_size=4,
                            decode_window=4, prefill_chunk=8,
                            max_seq_len=32, q_block=2, warm=False)
    assert row["roofline_ms"] < row["roofline_ms_fp"]
    assert row["kv_bytes_ratio"] <= 0.5
    assert row["outputs_equal"] is True
    assert row["pages_leaked"] == 0
    row = sb._measure_weight_only(gpt.cfg, gpt, gbps=819.0,
                                  prompt_len=7, new_tokens=6,
                                  qmodel=gpt_q, warm=False)
    assert row["roofline_ms"] < row["roofline_ms_fp"]
    assert row["weight_bytes_ratio"] < 0.5
    assert row["outputs_equal"] is True

#!/usr/bin/env python
"""AOT-lower the FRAMEWORK-CAPTURED GPT-13B train step on 32 virtual
devices (VERDICT r4 item 9: prove the real capture path, not a twin).

Unlike ``aot_gpt13b.py`` (a hand-written scan transformer over explicit
param pytrees), this drives the REAL user path at 13B scale:

    with paddle.LazyGuard():                 # abstract params, no RAM
        model = GPTForCausalLM(cfg_13b)
    shard_gpt(model, mesh, dp, mp)           # GSPMD annotations on SDS
    amp.decorate(O2, master_weight=True)     # abstract retype to bf16
    DygraphShardingOptimizer(AdamW, stage=1) # ZeRO-1 moments+master
    jit.aot_lower(train_step, ids, labels)   # discovery capture, abstract

What this proves that the twin cannot: the to_static discovery tracker,
autograd tape, AMP decoration, shard_gpt annotations and the ZeRO
in-trace constraints all survive 13B-scale tracing — no constant bloat
(a single materialized weight would be 100+ MB in the HLO), no sharding
loss (asserted on the compiled executable's input shardings), and the
compiled step's per-device residency fits v5e HBM.

Residency accounting note: optimizer moments / fp32 master weights are
CREATED by this first-step program (zeros/cast inside the trace), so
they are outputs, not donated inputs — same per-device residency as the
steady state, where they alias as donated input/output pairs.
"""
from __future__ import annotations

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

if os.environ.get("JAX_PLATFORMS") == "cpu":
    # the axon sitecustomize pins jax_platforms via jax.config, which
    # IGNORES the env var — force the config before backends initialize
    import jax

    jax.config.update("jax_platforms", "cpu")

V5E_HBM = 16 * 1024 ** 3


def main():
    import jax

    import paddle_tpu as paddle
    import paddle_tpu.amp as amp
    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed.fleet.sharding_optimizer import \
        DygraphShardingOptimizer
    from paddle_tpu.distributed.fleet.topology import \
        HybridCommunicateGroup
    from paddle_tpu.models.gpt import (GPTConfig, GPTForCausalLM,
                                       shard_gpt)

    n, dp, mp = 32, 4, 8
    assert len(jax.devices()) >= n, "needs 32 virtual devices"
    cfg = GPTConfig(vocab_size=50304, hidden_size=5120, num_layers=40,
                    num_heads=40, max_seq_len=2048, dropout=0.0,
                    recompute=True, use_flash_attention=False)
    t0 = time.time()
    with paddle.LazyGuard():
        model = GPTForCausalLM(cfg)
    t_build = time.time() - t0
    mesh = dist.ProcessMesh(np.arange(n).reshape(dp, mp), ["dp", "mp"])
    shard_gpt(model, mesh, dp_axis="dp", mp_axis="mp")
    model.train()
    opt_inner = paddle.optimizer.AdamW(learning_rate=1e-4,
                                       parameters=model.parameters())
    model, opt_inner = amp.decorate(models=model, optimizers=opt_inner,
                                    level="O2", dtype="bfloat16",
                                    master_weight=True)
    # ZeRO-1 over dp for moments + fp32 master (in-trace constraints);
    # hcg device order (1,1,dp,1,mp) == ProcessMesh (dp, mp) row-major
    hcg = HybridCommunicateGroup(dp_degree=1, pp_degree=1,
                                 sharding_degree=dp, sep_degree=1,
                                 mp_degree=mp)
    # rename compose base: the ZeRO axis in hcg is "sharding"; params
    # are annotated over ("dp","mp") — compose falls back to free dims
    opt = DygraphShardingOptimizer(opt_inner, hcg, stage=1)

    def train_step(ids, labels):
        with amp.auto_cast(level="O2", dtype="bfloat16"):
            loss = model(ids, labels)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    batch, seq = 32, cfg.max_seq_len
    ids = dist.shard_tensor(
        np.zeros((batch, seq), np.int32), mesh,
        [dist.Shard(0), dist.Replicate()])
    labels = dist.shard_tensor(
        np.zeros((batch, seq), np.int32), mesh,
        [dist.Shard(0), dist.Replicate()])

    t0 = time.time()
    lowered = paddle.jit.aot_lower(train_step, ids, labels)
    t_lower = time.time() - t0

    # constant-bloat check: no materialized weight in the HLO (a single
    # fp32 5120x5120 constant is 100 MB of MLIR text)
    text_len = len(lowered.as_text())
    assert text_len < 200 * 1024 * 1024, \
        f"suspicious HLO size {text_len} — constant bloat?"

    t0 = time.time()
    compiled = lowered.compile()
    t_compile = time.time() - t0

    # sharding-loss check: TP'd weight inputs must still carry "mp".
    # str(s) covers NamedSharding AND GSPMD/HloSharding reprs; guard
    # against a representation that names no axes at all (then this
    # check proves nothing and must say so rather than pass or fail
    # spuriously after the multi-minute compile)
    in_sh = jax.tree_util.tree_leaves(compiled.input_shardings[0])
    reprs = [str(getattr(s, "spec", None) or s) for s in in_sh]
    named = sum("mp" in r for r in reprs)
    devicey = sum("devices=" in r or "mp" in r or "dp" in r
                  for r in reprs)
    assert devicey, f"input shardings unreadable: {reprs[:3]}"
    assert named >= 4 * cfg.num_layers, \
        f"TP sharding lost in lowering: only {named} mp-sharded inputs"
    mem = compiled.memory_analysis()
    resident = None
    if mem:
        resident = mem.peak_memory_in_bytes + mem.argument_size_in_bytes
    print(f"13B CAPTURE lowered+compiled: build {t_build:.1f}s, "
          f"trace+lower {t_lower:.1f}s, compile {t_compile:.1f}s, "
          f"hlo {text_len/1e6:.1f} MB, "
          f"resident/device {resident/1024**3 if resident else -1:.2f} "
          f"GiB (v5e HBM 16 GiB)", flush=True)
    assert resident is not None and resident < V5E_HBM, \
        f"captured 13B step does not fit v5e HBM: {resident}"
    print("AOT CAPTURE 13B OK")


if __name__ == "__main__":
    sys.exit(main())

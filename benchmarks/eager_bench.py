#!/usr/bin/env python
"""Eager per-op dispatch cost micro-bench (VERDICT r2 weak #5): quantifies
the jax.vjp linearization that dispatch.apply performs on every forward op
when gradients are enabled. Run on CPU (eager on the tunnelled TPU is
dispatch-latency-bound regardless). Emits one JSON line."""
from __future__ import annotations

import json
import time

import numpy as np


def main():
    import jax
    jax.config.update("jax_platforms", "cpu")

    import paddle_tpu as paddle

    paddle.seed(0)
    lin = paddle.nn.Linear(256, 256)
    x = paddle.to_tensor(
        np.random.default_rng(0).normal(size=(64, 256)).astype("float32"))

    def fwd_nograd(n):
        with paddle.no_grad():
            for _ in range(n):
                y = lin(x)
        return float(y.numpy().sum())

    def fwd_grad(n):
        for _ in range(n):
            y = lin(x)
        return float(y.numpy().sum())

    def fwd_bwd(n):
        for _ in range(n):
            loss = lin(x).sum()
            loss.backward()
            lin.weight.clear_grad()
            lin.bias.clear_grad()
        return float(loss.numpy())

    def t(fn, n=300):
        fn(20)  # warm
        t0 = time.perf_counter()
        fn(n)
        return (time.perf_counter() - t0) / n * 1e6  # us/op

    a = t(fwd_nograd)
    b = t(fwd_grad)
    c = t(fwd_bwd, n=150)
    print(json.dumps({
        "metric": "eager_dispatch_us_per_op",
        "fwd_no_grad_us": round(a, 1),
        "fwd_grad_enabled_us": round(b, 1),
        "fwd_bwd_us": round(c, 1),
        "linearize_overhead_x": round(b / a, 2),
        "note": ("linearization is LAZY (built at first backward): "
                 "grad-enabled forwards pay only tape bookkeeping; "
                 "jax.vjp cost moves into fwd_bwd where it runs once"),
    }))


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Attribute GPT-124M step time to components WITHOUT a device profiler.

The axon environment exports no xprof device events (round 4), so this
uses differential window timing: each variant changes exactly one
component of the training step; K-step scanned windows (one dispatch,
pre-staged inputs) give wall times whose DIFFERENCES isolate that
component's cost. Variants:

  full            the bench step (AdamW, CE loss, 12 layers, remat)
  sgd             AdamW -> plain SGD        => optimizer update cost
  mean_loss       CE -> logits.mean()       => CE + lm_head vjp cost
  no_head         loss on hidden states     => + lm_head GEMM cost
  layers_6        12 -> 6 layers            => per-layer encoder cost
  fwd_only        no backward/optimizer     => backward multiple
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np


def _window_time(step, batch_fn, K=30, repeats=3):
    import paddle_tpu as paddle
    for _ in range(2):
        loss = step(*batch_fn())
    float(loss)
    w = paddle.jit.WindowRunner(step, batch_fn(), length=K)
    stacks = w.stage([batch_fn() for _ in range(K)])
    float(w.run(*stacks, outputs="last"))
    dt = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        float(w.run(*stacks, outputs="last"))
        dt = min(dt, time.perf_counter() - t0)
    return dt / K


def main():
    import gc

    import paddle_tpu as paddle
    import paddle_tpu.amp as amp
    from paddle_tpu.incubate import autotune
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    autotune.set_config({"kernel": {"enable": True}})
    batch, seq = 8, 1024
    results = {}

    def build(num_layers=12, opt_kind="adamw",
              policy="dots_and_kernels_saveable"):
        cfg = GPTConfig(vocab_size=50304, hidden_size=768,
                        num_layers=num_layers, num_heads=12,
                        max_seq_len=1024, dropout=0.0, recompute=True,
                        recompute_policy=policy)
        paddle.seed(0)
        model = GPTForCausalLM(cfg)
        model.train()
        if opt_kind == "adamw":
            opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                         parameters=model.parameters())
        else:
            opt = paddle.optimizer.SGD(learning_rate=1e-4,
                                       parameters=model.parameters())
        model, opt = amp.decorate(models=model, optimizers=opt,
                                  level="O2", dtype="bfloat16",
                                  master_weight=True)
        return cfg, model, opt

    rng = np.random.default_rng(0)

    def batch_fn():
        ids = rng.integers(0, 50304, (batch, seq)).astype(np.int32)
        lab = rng.integers(0, 50304, (batch, seq)).astype(np.int32)
        return paddle.to_tensor(ids), paddle.to_tensor(lab)

    def run(name, step):
        ms = _window_time(step, batch_fn) * 1e3
        results[name] = round(ms, 2)
        print(f"{name}: {ms:.2f} ms/step", file=sys.stderr, flush=True)
        gc.collect()

    variants = sys.argv[1:] or ["full", "sgd", "mean_loss", "no_head",
                                "layers_6", "fwd_only"]

    if "full" in variants:
        cfg, model, opt = build()

        @paddle.jit.to_static
        def full(ids, labels):
            with amp.auto_cast(level="O2", dtype="bfloat16"):
                loss = model(ids, labels)
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss
        run("full", full)
        del model, opt, full

    if "sgd" in variants:
        cfg, model, opt = build(opt_kind="sgd")

        @paddle.jit.to_static
        def sgd_step(ids, labels):
            with amp.auto_cast(level="O2", dtype="bfloat16"):
                loss = model(ids, labels)
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss
        run("sgd", sgd_step)
        del model, opt, sgd_step

    if "mean_loss" in variants:
        cfg, model, opt = build()

        @paddle.jit.to_static
        def mean_loss(ids, labels):
            with amp.auto_cast(level="O2", dtype="bfloat16"):
                logits = model(ids)          # [B, S, V]
                loss = logits.astype("float32").mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss
        run("mean_loss", mean_loss)
        del model, opt, mean_loss

    if "no_head" in variants:
        cfg, model, opt = build()
        gpt_body = getattr(model, "gpt", None) or model._layers.gpt

        @paddle.jit.to_static
        def no_head(ids, labels):
            with amp.auto_cast(level="O2", dtype="bfloat16"):
                h = gpt_body(ids)            # hidden states only
                loss = h.astype("float32").mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss
        run("no_head", no_head)
        del model, opt, no_head, gpt_body

    if "layers_6" in variants:
        cfg, model, opt = build(num_layers=6)

        @paddle.jit.to_static
        def six(ids, labels):
            with amp.auto_cast(level="O2", dtype="bfloat16"):
                loss = model(ids, labels)
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss
        run("layers_6", six)
        del model, opt, six

    if "fwd_only" in variants:
        cfg, model, opt = build()
        model.eval()

        @paddle.jit.to_static
        def fwd(ids, labels):
            with amp.auto_cast(level="O2", dtype="bfloat16"):
                loss = model(ids, labels)
            return loss
        run("fwd_only", fwd)
        del model, opt, fwd

    if "relu" in variants:
        # gelu(tanh) -> relu in the MLP: isolates the transcendental
        # (VPU) cost of gelu fwd + bwd + remat recompute
        from paddle_tpu.models import gpt as gpt_mod
        import paddle_tpu.nn.functional as F
        orig_fwd = gpt_mod.GPTMLP.forward
        gpt_mod.GPTMLP.forward = \
            lambda self, x: self.fc2(F.relu(self.fc1(x)))
        try:
            cfg, model, opt = build()

            @paddle.jit.to_static
            def relu_step(ids, labels):
                with amp.auto_cast(level="O2", dtype="bfloat16"):
                    loss = model(ids, labels)
                loss.backward()
                opt.step()
                opt.clear_grad()
                return loss
            run("relu", relu_step)
            del model, opt, relu_step
        finally:
            gpt_mod.GPTMLP.forward = orig_fwd

    if "xla_ln" in variants:
        # LayerNorm via jnp instead of the Pallas kernel: the custom
        # call is a fusion barrier; XLA may fuse the jnp form into the
        # surrounding residual-add/cast chains and win in-context
        import os
        os.environ["PDTPU_NORM_BACKEND"] = "xla"
        try:
            cfg, model, opt = build()

            @paddle.jit.to_static
            def xla_ln_step(ids, labels):
                with amp.auto_cast(level="O2", dtype="bfloat16"):
                    loss = model(ids, labels)
                loss.backward()
                opt.step()
                opt.clear_grad()
                return loss
            run("xla_ln", xla_ln_step)
            del model, opt, xla_ln_step
        finally:
            os.environ.pop("PDTPU_NORM_BACKEND", None)

    if "save_names" in variants:
        # transformer_saveable: ln/gelu outputs saved across backward
        cfg, model, opt = build(policy="transformer_saveable")

        @paddle.jit.to_static
        def save_names_step(ids, labels):
            with amp.auto_cast(level="O2", dtype="bfloat16"):
                loss = model(ids, labels)
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss
        run("save_names", save_names_step)
        del model, opt, save_names_step

    if "ln_off" in variants:
        # LayerNorm -> identity: upper bound on ALL norm-related cost
        from paddle_tpu.nn import layers as nl
        orig_ln = nl.LayerNorm.forward
        nl.LayerNorm.forward = lambda self, x: x
        try:
            cfg, model, opt = build()

            @paddle.jit.to_static
            def ln_off_step(ids, labels):
                with amp.auto_cast(level="O2", dtype="bfloat16"):
                    loss = model(ids, labels)
                loss.backward()
                opt.step()
                opt.clear_grad()
                return loss
            run("ln_off", ln_off_step)
            del model, opt, ln_off_step
        finally:
            nl.LayerNorm.forward = orig_ln

    # ----------------------------------------------------- ResNet50 --
    # VERDICT r5 item 2: conv is only ~5 ms of the 25 ms step (the r4
    # calibration refuted the MXU-underfill excuse) — locate the other
    # ~20 ms: BN? optimizer? data movement?
    def build_resnet(opt_kind="momentum"):
        from paddle_tpu.vision.models import resnet50
        paddle.seed(0)
        model = resnet50(num_classes=1000)
        model.train()
        if opt_kind == "momentum":
            opt = paddle.optimizer.Momentum(
                learning_rate=0.1, momentum=0.9,
                parameters=model.parameters())
        else:
            opt = None
        if opt is not None:
            model, opt = amp.decorate(models=model, optimizers=opt,
                                      level="O2", dtype="bfloat16",
                                      master_weight=True)
        else:
            model = amp.decorate(models=model, level="O2",
                                 dtype="bfloat16")
        return model, opt

    rbatch = 32

    def rbatch_fn():
        x = rng.normal(size=(rbatch, 3, 224, 224)).astype(np.float32)
        y = rng.integers(0, 1000, (rbatch,)).astype(np.int64)
        return paddle.to_tensor(x), paddle.to_tensor(y)

    def resnet_step(model, opt):
        @paddle.jit.to_static
        def step(x, y):
            with amp.auto_cast(level="O2", dtype="bfloat16"):
                loss = paddle.nn.functional.cross_entropy(model(x), y)
            loss.backward()
            if opt is not None:
                opt.step()
                opt.clear_grad()
            return loss
        return step

    def run_resnet(name, model, opt):
        step = resnet_step(model, opt)
        ms = _window_time(step, rbatch_fn, K=6) * 1e3
        results[name] = round(ms, 2)
        print(f"{name}: {ms:.2f} ms/step", file=sys.stderr, flush=True)
        gc.collect()

    if "resnet_full" in variants:
        model, opt = build_resnet()
        run_resnet("resnet_full", model, opt)
        del model, opt

    if "resnet_bn_off" in variants:
        from paddle_tpu.nn import layers as nl
        orig_bn = nl.BatchNorm2D.forward
        nl.BatchNorm2D.forward = lambda self, x: x
        try:
            model, opt = build_resnet()
            run_resnet("resnet_bn_off", model, opt)
            del model, opt
        finally:
            nl.BatchNorm2D.forward = orig_bn

    if "resnet_opt_off" in variants:
        model, opt = build_resnet(opt_kind="none")
        run_resnet("resnet_opt_off", model, opt)
        del model, opt

    if "resnet_fwd_only" in variants:
        model, _ = build_resnet(opt_kind="none")
        model.eval()

        @paddle.jit.to_static
        def fwd(x, y):
            with amp.auto_cast(level="O2", dtype="bfloat16"):
                loss = paddle.nn.functional.cross_entropy(model(x), y)
            return loss
        ms = _window_time(fwd, rbatch_fn, K=6) * 1e3
        results["resnet_fwd_only"] = round(ms, 2)
        print(f"resnet_fwd_only: {ms:.2f} ms/step", file=sys.stderr,
              flush=True)
        del model, fwd
        gc.collect()

    # derived attributions
    d = {}
    if "resnet_full" in results and "resnet_bn_off" in results:
        d["resnet_bn_ms"] = round(
            results["resnet_full"] - results["resnet_bn_off"], 2)
    if "resnet_full" in results and "resnet_opt_off" in results:
        d["resnet_momentum_ms"] = round(
            results["resnet_full"] - results["resnet_opt_off"], 2)
    if "resnet_full" in results and "resnet_fwd_only" in results:
        d["resnet_bwd_plus_opt_ms"] = round(
            results["resnet_full"] - results["resnet_fwd_only"], 2)
    if "full" in results and "sgd" in results:
        d["adamw_minus_sgd_ms"] = round(results["full"] - results["sgd"], 2)
    if "full" in results and "mean_loss" in results:
        d["ce_loss_ms"] = round(results["full"] - results["mean_loss"], 2)
    if "mean_loss" in results and "no_head" in results:
        d["lm_head_gemms_ms"] = round(
            results["mean_loss"] - results["no_head"], 2)
    if "full" in results and "layers_6" in results:
        d["per_layer_ms"] = round(
            (results["full"] - results["layers_6"]) / 6.0, 2)
    if "full" in results and "fwd_only" in results:
        d["bwd_plus_opt_ms"] = round(
            results["full"] - results["fwd_only"], 2)
    if "full" in results and "relu" in results:
        d["gelu_minus_relu_ms"] = round(
            results["full"] - results["relu"], 2)
    if "full" in results and "xla_ln" in results:
        d["pallas_ln_minus_xla_ln_ms"] = round(
            results["full"] - results["xla_ln"], 2)
    if "full" in results and "ln_off" in results:
        d["ln_total_ms"] = round(results["full"] - results["ln_off"], 2)
    print(json.dumps({"variants_ms": results, "derived": d}, indent=1))


if __name__ == "__main__":
    main()

"""Fused vs per-param optimizer micro-bench.

Measures, at BERT-base and ResNet50 parameter-set shapes:

- traced-step HLO op counts (total + arithmetic "update ops") of a
  captured optimizer-only step under the fused flat-bucket path vs the
  per-param path — the acceptance bar is >= 10x fewer update ops at
  BERT-base scale;
- eager update wall time per step (fused vs per-param) and the number
  of fused-kernel dispatches per step (O(buckets), not O(params)).

Run standalone (`python benchmarks/optimizer_bench.py [--small]`) for a
JSON report, or through bench.py which embeds a cached row
(``secondary_optimizer``). ``--small`` shrinks hidden sizes (op counts
are size-independent; only timings change) so the report runs in
seconds on CPU — the structural op-count ratio is what the tests pin.
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

ARITH = {
    "add", "sub", "mul", "div", "sqrt", "rsqrt", "max", "min", "pow",
    "integer_pow", "neg", "sign", "abs", "square",
}


def bert_base_shapes(hidden=768, layers=12, vocab=30522, seq=512):
    """The BERT-base parameter set (structurally exact: one entry per
    parameter tensor, ~200 tensors)."""
    h, i4 = hidden, 4 * hidden
    shapes = [(vocab, h), (seq, h), (2, h), (h,), (h,)]  # embeddings + LN
    for _ in range(layers):
        shapes += [(h, h), (h,)] * 4          # q/k/v/out
        shapes += [(h,), (h,)]                # attn LN
        shapes += [(h, i4), (i4,), (i4, h), (h,)]  # ffn
        shapes += [(h,), (h,)]                # ffn LN
    shapes += [(h, h), (h,), (h,), (h,), (h, 2), (2,)]  # pooler/heads
    return shapes


def resnet50_shapes(width=64):
    """ResNet50 parameter set (conv/bn/fc tensor structure)."""
    w = width
    shapes = [(w, 3, 7, 7), (w,), (w,)]
    cfg = [(3, w, w * 4), (4, w * 2, w * 8), (6, w * 4, w * 16),
           (3, w * 8, w * 32)]
    inp = w
    for blocks, mid, out in cfg:
        for b in range(blocks):
            shapes += [(mid, inp, 1, 1), (mid,), (mid,)]
            shapes += [(mid, mid, 3, 3), (mid,), (mid,)]
            shapes += [(out, mid, 1, 1), (out,), (out,)]
            if b == 0:
                shapes += [(out, inp, 1, 1), (out,), (out,)]
            inp = out
    shapes += [(inp, 1000), (1000,)]
    return shapes


def _make_opt(shapes, kind, fused, seed=0):
    import paddle_tpu as pt
    from paddle_tpu import optimizer as opt
    from paddle_tpu.core import state as st
    st.set_flags({"fused_opt": fused})
    rng = np.random.default_rng(seed)
    params = [pt.Parameter(rng.normal(size=s).astype("float32") * 0.02)
              for s in shapes]
    grads = [rng.integers(-2, 3, s).astype("float32") for s in shapes]
    cls = {"adamw": opt.AdamW, "adam": opt.Adam, "sgd": opt.SGD,
           "momentum": opt.Momentum}[kind]
    o = cls(learning_rate=1e-3, parameters=params)
    return params, grads, o


def _set_grads(params, grads):
    import paddle_tpu as pt
    for p, g in zip(params, grads):
        p.grad = pt.to_tensor(g)


def _count(jaxpr):
    total = arith = 0
    stack = [jaxpr.jaxpr if hasattr(jaxpr, "jaxpr") else jaxpr]
    while stack:
        j = stack.pop()
        for eqn in j.eqns:
            total += 1
            if eqn.primitive.name in ARITH:
                arith += 1
            for v in eqn.params.values():
                vs = v if isinstance(v, (list, tuple)) else (v,)
                for x in vs:
                    inner = getattr(x, "jaxpr", None)
                    if inner is not None:
                        stack.append(inner)
    return total, arith


def hlo_op_counts(shapes, kind="adamw", fused=True):
    """(total_eqns, arith_eqns) of the captured optimizer-only step."""
    import jax

    import paddle_tpu as pt
    from paddle_tpu.core import state as st
    entry_flag = st.get_flag("fused_opt")
    try:
        params, grads, o = _make_opt(shapes, kind, fused)
        _set_grads(params, grads)

        @pt.jit.to_static
        def upd():
            o.step()
            o.clear_grad(set_to_zero=True)
            return params[0]

        upd()
        exe = list(upd._cache.values())[0]
        vals = [t._read() for t in exe.capt_state]
        jaxpr = jax.make_jaxpr(exe._pure)(*vals)
        return _count(jaxpr)
    finally:
        st.set_flags({"fused_opt": entry_flag})


def eager_step_time(shapes, kind="adamw", fused=True, iters=10):
    """(seconds per eager optimizer.step, fused-kernel calls per step,
    bucket count)."""
    import jax

    from paddle_tpu.core import state as st
    from paddle_tpu.ops.pallas import fused_optimizer as fo
    entry_flag = st.get_flag("fused_opt")
    calls = [0]
    orig = fo.fused_update

    def counting(*a, **k):
        calls[0] += 1
        return orig(*a, **k)
    fo.fused_update = counting
    try:
        params, grads, o = _make_opt(shapes, kind, fused)
        for _ in range(2):  # warm (bucket build + op compile caches)
            _set_grads(params, grads)
            o.step()
            o.clear_grad()
        jax.block_until_ready(params[0]._read())
        calls[0] = 0
        t0 = time.perf_counter()
        for _ in range(iters):
            _set_grads(params, grads)
            o.step()
            o.clear_grad()
        jax.block_until_ready(params[0]._read())
        dt = (time.perf_counter() - t0) / iters
    finally:
        fo.fused_update = orig
        st.set_flags({"fused_opt": entry_flag})
    buckets = len(o._flat or ())
    return dt, calls[0] // iters, buckets


def bench_row(small=False, kind="adamw"):
    sets = {
        "bert_base": bert_base_shapes(hidden=64 if small else 768,
                                      vocab=512 if small else 30522,
                                      seq=64 if small else 512),
        "resnet50": resnet50_shapes(width=8 if small else 64),
    }
    out = {"metric": "optimizer_fused_update", "optimizer": kind,
           "small": bool(small)}
    for name, shapes in sets.items():
        tot_f, ar_f = hlo_op_counts(shapes, kind, fused=True)
        tot_p, ar_p = hlo_op_counts(shapes, kind, fused=False)
        dt_f, calls, buckets = eager_step_time(shapes, kind, fused=True)
        dt_p, _, _ = eager_step_time(shapes, kind, fused=False)
        out[name] = {
            "params": len(shapes),
            "elements": int(sum(int(np.prod(s)) for s in shapes)),
            "hlo_ops_per_param": tot_p, "hlo_ops_fused": tot_f,
            "update_ops_per_param": ar_p, "update_ops_fused": ar_f,
            "update_op_reduction_x": round(ar_p / max(ar_f, 1), 1),
            "eager_step_ms_per_param": round(dt_p * 1e3, 3),
            "eager_step_ms_fused": round(dt_f * 1e3, 3),
            "eager_speedup_x": round(dt_p / max(dt_f, 1e-9), 2),
            "fused_kernel_calls_per_step": calls,
            "buckets": buckets,
        }
    return out


def main():
    small = "--small" in sys.argv or \
        __import__("jax").default_backend() != "tpu"
    print(json.dumps(bench_row(small=small), indent=1))


if __name__ == "__main__":
    main()

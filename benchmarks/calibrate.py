#!/usr/bin/env python
"""On-chip calibration: measured bf16 matmul TF/s at GPT-124M's actual GEMM
shapes, attention fwd/bwd TF/s, and a matmul-only roofline for the bench
config. Emits one JSON object (and writes it to argv[1] if given).

Methodology — the axon tunnel adds milliseconds of fixed per-dispatch
latency and ``block_until_ready`` does not actually wait (measured: it
"times" an 8192^3 matmul at 57 PF/s), so naive per-call timing is garbage
at these op sizes. Instead each op runs R times *inside one compiled
program* (lax.scan over R distinct stacked inputs, accumulating into the
output so nothing can be elided or hoisted), timed at two values of R with
host-readback sync; the slope (t_R2 - t_R1) / (R2 - R1) is pure kernel
time, with dispatch overhead and sync cost cancelled.

The roofline is matmul+attention kernel time only (elementwise, softmax,
optimizer, dispatch all ride free in its idealised world), so real step
time must exceed it; the ratio is the schedulable headroom.
"""
from __future__ import annotations

import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np


def _log(msg):
    print(msg, file=sys.stderr, flush=True)


def _sync(x):
    """True device sync: host readback of a scalar (block_until_ready lies
    over the tunnel — see module docstring)."""
    return float(jnp.asarray(x).reshape(-1)[0].astype(jnp.float32))


def _time_call(fn, *args, iters=4, warmup=2):
    for _ in range(warmup):
        out = fn(*args)
    _sync(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    _sync(out)
    return (time.perf_counter() - t0) / iters


def _scanned_matmul(m, k, n, reps, dtype=jnp.bfloat16, seed=0):
    """One jit program running ``reps`` sequential [m,k]@[k,n] matmuls.
    One operand is perturbed by the (traced) iteration index so XLA cannot
    CSE or hoist the dot. The perturbing add rides in the slope (it does
    NOT cancel), so it goes on the SMALLER operand — its elementwise cost
    is then 1-3% of the GEMM at these shapes, the stated accuracy of this
    calibration."""
    rng = np.random.default_rng(seed)
    a = jnp.asarray(rng.normal(size=(m, k)) * 0.1, dtype)
    b = jnp.asarray(rng.normal(size=(k, n)) * 0.1, dtype)
    perturb_a = m * k <= k * n

    @jax.jit
    def f(a, b):
        def body(c, i):
            eps = i.astype(dtype) * 1e-6
            if perturb_a:
                return c + (a + eps) @ b, None
            return c + a @ (b + eps), None
        return jax.lax.scan(body, jnp.zeros((m, n), dtype),
                            jnp.arange(reps))[0]

    return f, (a, b)


def measure_matmul(m, k, n, r1=32, r2=256):
    """Kernel-only TF/s via the two-R slope (fixed dispatch+sync overhead
    cancels; large r2-r1 swamps the tunnel's per-call jitter)."""
    f1, a1 = _scanned_matmul(m, k, n, r1)
    f2, a2 = _scanned_matmul(m, k, n, r2)
    t1 = _time_call(f1, *a1)
    t2 = _time_call(f2, *a2)
    per_op = max((t2 - t1) / (r2 - r1), 1e-9)
    return 2.0 * m * k * n / per_op / 1e12, per_op


def _scanned_attention(batch, heads, seq, head_dim, reps, causal, bwd):
    from paddle_tpu.ops.pallas import flash_attention as fa

    rng = np.random.default_rng(0)
    shp = (batch, seq, heads, head_dim)
    q = jnp.asarray(rng.normal(size=shp) * 0.1, jnp.bfloat16)
    k = jnp.asarray(rng.normal(size=shp) * 0.1, jnp.bfloat16)
    v = jnp.asarray(rng.normal(size=shp) * 0.1, jnp.bfloat16)

    def one(q, k, v):
        return fa.flash_attention(q, k, v, causal=causal)

    if not bwd:
        @jax.jit
        def f(q, k, v):
            def body(c, i):
                return c + one(q + i.astype(q.dtype) * 1e-6, k, v), None
            z = jnp.zeros(shp, jnp.bfloat16)
            return jax.lax.scan(body, z, jnp.arange(reps))[0]
    else:
        grad = jax.grad(
            lambda q, k, v: one(q, k, v).astype(jnp.float32).sum(),
            argnums=(0, 1, 2))

        @jax.jit
        def f(q, k, v):
            def body(c, i):
                dq, dk, dv = grad(q + i.astype(q.dtype) * 1e-6, k, v)
                return c + dq.astype(jnp.bfloat16), None
            z = jnp.zeros(shp, jnp.bfloat16)
            return jax.lax.scan(body, z, jnp.arange(reps))[0]

    return f, (q, k, v)


def measure_attention(batch, heads, seq, head_dim, causal=True,
                      r1=8, r2=48):
    res = {}
    for tag, bwd in (("fwd", False), ("bwd", True)):
        f1, a1 = _scanned_attention(batch, heads, seq, head_dim, r1,
                                    causal, bwd)
        f2, a2 = _scanned_attention(batch, heads, seq, head_dim, r2,
                                    causal, bwd)
        t1 = _time_call(f1, *a1)
        t2 = _time_call(f2, *a2)
        per_op = max((t2 - t1) / (r2 - r1), 1e-9)
        flops = 4.0 * batch * heads * seq * seq * head_dim
        if causal:
            flops *= 0.5
        if bwd:
            flops *= 2.5  # dQ,dK,dV + recompute
        res[tag] = {"tflops": round(flops / per_op / 1e12, 2),
                    "ms": round(per_op * 1e3, 3)}
    return res


def calibrate(batch=8, seq=1024, hidden=768, heads=12, layers=12,
              vocab=50304, ffn_mult=4):
    """Roofline for the bench GPT-124M config at (batch, seq)."""
    tokens = batch * seq
    head_dim = hidden // heads

    gemms = {
        # name: (m, k, n, count per step)
        "qkv": (tokens, hidden, 3 * hidden, layers),
        "attn_proj": (tokens, hidden, hidden, layers),
        "ffn_up": (tokens, hidden, ffn_mult * hidden, layers),
        "ffn_down": (tokens, ffn_mult * hidden, hidden, layers),
        "lm_head": (tokens, hidden, vocab, 1),
    }

    out = {"device": str(jax.devices()[0].device_kind),
           "batch": batch, "seq": seq,
           "method": "scan-slope (see module docstring)", "gemms": {}}

    for s in (8192,):
        tf, dt = measure_matmul(s, s, s)
        out["gemms"][f"square_{s}"] = {
            "shape": [s, s, s], "tflops": round(tf, 2),
            "ms": round(dt * 1e3, 3)}
        _log(f"square_{s}: {tf:.1f} TF/s ({dt*1e3:.3f} ms)")

    total_matmul_time = 0.0
    total_matmul_flops = 0.0
    for name, (m, k, n, cnt) in gemms.items():
        tf, dt = measure_matmul(m, k, n)
        tf_dx, dt_dx = measure_matmul(m, n, k)      # dX = dY @ W^T
        tf_dw, dt_dw = measure_matmul(k, m, n)      # dW = X^T @ dY
        out["gemms"][name] = {
            "shape": [m, k, n], "count": cnt,
            "fwd_tflops": round(tf, 2), "dx_tflops": round(tf_dx, 2),
            "dw_tflops": round(tf_dw, 2),
            "fwd_ms": round(dt * 1e3, 3)}
        _log(f"{name}: fwd {tf:.1f} / dx {tf_dx:.1f} / dw {tf_dw:.1f} TF/s")
        total_matmul_time += cnt * (dt + dt_dx + dt_dw)
        total_matmul_flops += cnt * 3 * (2.0 * m * k * n)

    att = measure_attention(batch, heads, seq, head_dim)
    out["attention"] = dict(att, shape=[batch, heads, seq, head_dim],
                            causal=True)
    _log(f"attention: fwd {att['fwd']['tflops']} TF/s "
         f"({att['fwd']['ms']} ms), bwd {att['bwd']['tflops']} TF/s "
         f"({att['bwd']['ms']} ms)")
    att_time = layers * (att["fwd"]["ms"] + att["bwd"]["ms"]) / 1e3

    step_lb = total_matmul_time + att_time
    out["roofline"] = {
        "matmul_time_ms": round(total_matmul_time * 1e3, 2),
        "attention_time_ms": round(att_time * 1e3, 2),
        "step_time_lower_bound_ms": round(step_lb * 1e3, 2),
        "blended_matmul_tflops": round(
            total_matmul_flops / total_matmul_time / 1e12, 2),
        "note": ("lower bound: GEMM+attention kernel time only, zero "
                 "elementwise/softmax/optimizer/dispatch; real step time "
                 "must exceed this"),
    }
    return out


if __name__ == "__main__":
    res = calibrate()
    print(json.dumps(res, indent=2))
    if len(sys.argv) > 1:
        with open(sys.argv[1], "w") as f:
            json.dump(res, f, indent=2)

#!/usr/bin/env python
"""On-chip calibration: measured bf16 matmul TF/s at GPT-124M's actual GEMM
shapes, attention fwd/bwd TF/s, and a matmul-only roofline for the bench
config. Emits one JSON object (and writes it to argv[1] if given).

Methodology — the axon tunnel adds milliseconds of fixed per-dispatch
latency and ``block_until_ready`` does not actually wait (measured: it
"times" an 8192^3 matmul at 57 PF/s), so naive per-call timing is garbage
at these op sizes. Instead each op runs R times *inside one compiled
program* (lax.scan over R distinct stacked inputs, accumulating into the
output so nothing can be elided or hoisted), timed at two values of R with
host-readback sync; the slope (t_R2 - t_R1) / (R2 - R1) is pure kernel
time, with dispatch overhead and sync cost cancelled.

The roofline is matmul+attention kernel time only (elementwise, softmax,
optimizer, dispatch all ride free in its idealised world), so real step
time must exceed it; the ratio is the schedulable headroom.
"""
from __future__ import annotations

import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np


def _log(msg):
    print(msg, file=sys.stderr, flush=True)


def _sync(x):
    """True device sync: host readback of a scalar (block_until_ready lies
    over the tunnel — see module docstring)."""
    return float(jnp.asarray(x).reshape(-1)[0].astype(jnp.float32))


def _time_call(fn, *args, iters=4, warmup=2):
    for _ in range(warmup):
        out = fn(*args)
    _sync(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    _sync(out)
    return (time.perf_counter() - t0) / iters


def _scanned_matmul(m, k, n, reps, dtype=jnp.bfloat16, seed=0):
    """One jit program running ``reps`` sequential [m,k]@[k,n] matmuls.
    One operand is perturbed by the (traced) iteration index so XLA cannot
    CSE or hoist the dot. The perturbing add rides in the slope (it does
    NOT cancel), so it goes on the SMALLER operand — its elementwise cost
    is then 1-3% of the GEMM at these shapes, the stated accuracy of this
    calibration."""
    rng = np.random.default_rng(seed)
    a = jnp.asarray(rng.normal(size=(m, k)) * 0.1, dtype)
    b = jnp.asarray(rng.normal(size=(k, n)) * 0.1, dtype)
    perturb_a = m * k <= k * n

    @jax.jit
    def f(a, b):
        def body(c, i):
            eps = i.astype(dtype) * 1e-6
            if perturb_a:
                return c + (a + eps) @ b, None
            return c + a @ (b + eps), None
        return jax.lax.scan(body, jnp.zeros((m, n), dtype),
                            jnp.arange(reps))[0]

    return f, (a, b)


def measure_matmul(m, k, n, r1=32, r2=256):
    """Kernel-only TF/s via the two-R slope (fixed dispatch+sync overhead
    cancels; large r2-r1 swamps the tunnel's per-call jitter)."""
    f1, a1 = _scanned_matmul(m, k, n, r1)
    f2, a2 = _scanned_matmul(m, k, n, r2)
    t1 = _time_call(f1, *a1)
    t2 = _time_call(f2, *a2)
    per_op = max((t2 - t1) / (r2 - r1), 1e-9)
    return 2.0 * m * k * n / per_op / 1e12, per_op


def _scanned_attention(batch, heads, seq, head_dim, reps, causal, bwd):
    from paddle_tpu.ops.pallas import flash_attention as fa

    rng = np.random.default_rng(0)
    shp = (batch, seq, heads, head_dim)
    q = jnp.asarray(rng.normal(size=shp) * 0.1, jnp.bfloat16)
    k = jnp.asarray(rng.normal(size=shp) * 0.1, jnp.bfloat16)
    v = jnp.asarray(rng.normal(size=shp) * 0.1, jnp.bfloat16)

    def one(q, k, v):
        return fa.flash_attention(q, k, v, causal=causal)

    if not bwd:
        @jax.jit
        def f(q, k, v):
            def body(c, i):
                return c + one(q + i.astype(q.dtype) * 1e-6, k, v), None
            z = jnp.zeros(shp, jnp.bfloat16)
            return jax.lax.scan(body, z, jnp.arange(reps))[0]
    else:
        grad = jax.grad(
            lambda q, k, v: one(q, k, v).astype(jnp.float32).sum(),
            argnums=(0, 1, 2))

        @jax.jit
        def f(q, k, v):
            def body(c, i):
                # all three grads feed the carry so the dkv kernel cannot
                # be dead-code-eliminated from the timed program
                dq, dk, dv = grad(q + i.astype(q.dtype) * 1e-6, k, v)
                return c + (dq + dk + dv).astype(jnp.bfloat16), None
            z = jnp.zeros(shp, jnp.bfloat16)
            return jax.lax.scan(body, z, jnp.arange(reps))[0]

    return f, (q, k, v)


def measure_attention(batch, heads, seq, head_dim, causal=True,
                      r1=8, r2=48):
    res = {}
    for tag, bwd in (("fwd", False), ("bwd", True)):
        f1, a1 = _scanned_attention(batch, heads, seq, head_dim, r1,
                                    causal, bwd)
        f2, a2 = _scanned_attention(batch, heads, seq, head_dim, r2,
                                    causal, bwd)
        t1 = _time_call(f1, *a1)
        t2 = _time_call(f2, *a2)
        per_op = max((t2 - t1) / (r2 - r1), 1e-9)
        flops = 4.0 * batch * heads * seq * seq * head_dim
        if causal:
            flops *= 0.5
        if bwd:
            flops *= 2.5  # dQ,dK,dV + recompute
        res[tag] = {"tflops": round(flops / per_op / 1e12, 2),
                    "ms": round(per_op * 1e3, 3)}
    return res


def _scanned_norm(rows, hidden, reps, bwd):
    """One jit program running ``reps`` Pallas layer_norms (optionally
    + input/weight/bias grads), index-perturbed like the matmul scan."""
    from paddle_tpu.ops.pallas import norms

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(rows, hidden)) * 0.1, jnp.float32)
    w = jnp.ones((hidden,), jnp.float32)
    b = jnp.zeros((hidden,), jnp.float32)

    def one(x, w, b):
        return norms.layer_norm(x, w, b)

    if not bwd:
        @jax.jit
        def f(x, w, b):
            def body(c, i):
                return c + one(x + i.astype(x.dtype) * 1e-6, w, b), None
            return jax.lax.scan(body, jnp.zeros_like(x),
                                jnp.arange(reps))[0]
    else:
        grad = jax.grad(lambda x, w, b: one(x, w, b).sum(),
                        argnums=(0, 1, 2))

        @jax.jit
        def f(x, w, b):
            def body(c, i):
                dx, dw, db = grad(x + i.astype(x.dtype) * 1e-6, w, b)
                return c + dx + (dw.sum() + db.sum()), None
            return jax.lax.scan(body, jnp.zeros_like(x),
                                jnp.arange(reps))[0]

    return f, (x, w, b)


def measure_norm(rows, hidden, r1=16, r2=96):
    res = {}
    for tag, bwd in (("fwd", False), ("bwd", True)):
        f1, a1 = _scanned_norm(rows, hidden, r1, bwd)
        f2, a2 = _scanned_norm(rows, hidden, r2, bwd)
        per_op = max((_time_call(f2, *a2) - _time_call(f1, *a1))
                     / (r2 - r1), 1e-9)
        res[tag] = {"ms": round(per_op * 1e3, 4)}
    return res


def _scanned_fused_opt(n, reps):
    """One jit program running ``reps`` fused AdamW bucket updates on an
    ``n``-element f32 flat (the PR4 one-kernel-per-bucket path), state
    threaded through the scan carry so nothing is elided."""
    from paddle_tpu.ops.pallas import fused_optimizer as fo

    spec = fo.UpdateSpec(kind="adamw", beta1=0.9, beta2=0.999,
                         eps=1e-8, decay=0.01)
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.normal(size=(n,)) * 0.1, jnp.float32)
    g = jnp.asarray(rng.normal(size=(n,)) * 0.01, jnp.float32)
    m = jnp.zeros((n,), jnp.float32)
    v = jnp.zeros((n,), jnp.float32)

    @jax.jit
    def f(w, g, m, v):
        def body(carry, i):
            w, m, v, b1p, b2p = carry
            nw, _, nm, nv, nb1, nb2 = fo.fused_update(
                spec, w=w, g=g + i.astype(g.dtype) * 1e-9, lr=1e-3,
                m=m, v=v, b1p=b1p, b2p=b2p)
            return (nw, nm, nv, nb1, nb2), None
        init = (w, m, v, jnp.float32(0.9), jnp.float32(0.999))
        return jax.lax.scan(body, init, jnp.arange(reps))[0][0]

    return f, (w, g, m, v)


def measure_fused_optimizer(n, r1=8, r2=48):
    f1, a1 = _scanned_fused_opt(n, r1)
    f2, a2 = _scanned_fused_opt(n, r2)
    per_op = max((_time_call(f2, *a2) - _time_call(f1, *a1))
                 / (r2 - r1), 1e-9)
    return {"ms": round(per_op * 1e3, 4), "elements": n}


def measure_decode_dispatches(hidden=32, heads=4, vocab=96,
                              max_len=64, page_size=8, batch=2):
    """Per-layer op-dispatch count of ONE serving decode step, unfused
    vs megakernel (ISSUE 18) — counted EXACTLY by the profiler op-hook
    (``core.dispatch._profile_hook``, the ISSUE-12 instrumentation
    point) over an eager replay of the engine's step body at L=1 and
    L=2 tiny-GPT configs; the difference isolates the per-layer chain
    from the embedding/epilogue constants.  This is a COUNT, not a
    timing, so the tiny config is exact for any model depth/width: the
    number of dispatches per decode layer is shape-independent.  The
    megakernel target is ≤4/layer (ingress, paged attention, one
    reshape, egress) vs ~12 unfused."""
    import paddle_tpu as pp
    from paddle_tpu.core import dispatch as _dispatch
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.models.generation import (_gpt_decode,
                                              _gpt_decode_fused,
                                              _zero_pool,
                                              guarded_argmax,
                                              paged_slot_attention)
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    def count_ops(layers):
        pp.seed(0)
        cfg = GPTConfig(vocab_size=vocab, hidden_size=hidden,
                        num_layers=layers, num_heads=heads,
                        max_seq_len=max_len, dropout=0.0)
        model = GPTForCausalLM(cfg)
        model.eval()
        np_per = max_len // page_size
        bt = np.arange(1, 1 + batch * np_per, dtype=np.int32).reshape(
            batch, np_per)
        shape = (heads, 1 + batch * np_per, page_size,
                 hidden // heads)
        tok = Tensor(np.zeros((batch, 1), np.int32))
        pos = Tensor(np.zeros((batch,), np.int32))
        poison = Tensor(np.zeros((batch,), np.float32))
        btt = Tensor(bt)

        def run(fn):
            caches = [Tensor(a) for a in _zero_pool(shape, 2 * layers)]
            n = [0]

            def hook(name, t0, t1):
                n[0] += 1

            _dispatch._profile_hook = hook
            try:
                with pp.no_grad():
                    fn(caches)
            finally:
                _dispatch._profile_hook = None
            return n[0]

        def unfused(caches):
            def attend(q, k, v, kc, vc, p, ks=None, vs=None):
                return paged_slot_attention(q, k, v, kc, vc, p, btt)
            lg, _ = _gpt_decode(model, tok, pos, caches, attend=attend)
            guarded_argmax(lg, poison)

        def fused(caches):
            _gpt_decode_fused(model, tok, pos, btt, caches, poison)

        return run(unfused), run(fused)

    u1, m1 = count_ops(1)
    u2, m2 = count_ops(2)
    out = {
        "method": "op-hook dispatch count of one eager decode step "
                  "(L=2 minus L=1 isolates the per-layer chain)",
        "unfused_per_layer": u2 - u1,
        "megakernel_per_layer": m2 - m1,
        "unfused_other": 2 * u1 - u2,       # embedding + lm head/argmax
        "megakernel_other": 2 * m1 - m2,
    }
    _log(f"decode dispatches/layer: unfused {out['unfused_per_layer']}"
         f" -> megakernel {out['megakernel_per_layer']} (constants "
         f"{out['unfused_other']} -> {out['megakernel_other']})")
    return out


def _scanned_glue(rows, hidden, reps, bwd, fused):
    """One jit program running ``reps`` residual-add+layer-norm glue
    chains (optionally + input/weight/bias grads), index-perturbed like
    the matmul scan. ``fused`` picks the ISSUE-19 single-dispatch
    kernel; unfused is the dispatch chain the training blocks emit
    today (add, then the Pallas layer_norm). Both consume the residual
    AND the normed output so neither branch can be elided."""
    from paddle_tpu.ops.pallas import fused_residual_norm as frn
    from paddle_tpu.ops.pallas import norms

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(rows, hidden)) * 0.1, jnp.float32)
    y = jnp.asarray(rng.normal(size=(rows, hidden)) * 0.1, jnp.float32)
    w = jnp.ones((hidden,), jnp.float32)
    b = jnp.zeros((hidden,), jnp.float32)

    if fused:
        def one(x, y, w, b):
            res, o = frn.fused_residual_layer_norm(x, y, w, b)
            return res + o
    else:
        def one(x, y, w, b):
            res = x + y
            return res + norms.layer_norm(res, w, b)

    if not bwd:
        @jax.jit
        def f(x, y, w, b):
            def body(c, i):
                return c + one(x + i.astype(x.dtype) * 1e-6, y, w, b), None
            return jax.lax.scan(body, jnp.zeros_like(x),
                                jnp.arange(reps))[0]
    else:
        grad = jax.grad(lambda x, y, w, b: one(x, y, w, b).sum(),
                        argnums=(0, 1, 2, 3))

        @jax.jit
        def f(x, y, w, b):
            def body(c, i):
                dx, dy, dw, db = grad(x + i.astype(x.dtype) * 1e-6,
                                      y, w, b)
                return c + dx + dy + (dw.sum() + db.sum()), None
            return jax.lax.scan(body, jnp.zeros_like(x),
                                jnp.arange(reps))[0]

    return f, (x, y, w, b)


def measure_glue(rows, hidden, r1=16, r2=96):
    """Fused vs unfused training-glue kernel ms (fwd and bwd) via the
    two-R slope."""
    res = {}
    for kind, fused in (("fused", True), ("unfused", False)):
        res[kind] = {}
        for tag, bwd in (("fwd", False), ("bwd", True)):
            f1, a1 = _scanned_glue(rows, hidden, r1, bwd, fused)
            f2, a2 = _scanned_glue(rows, hidden, r2, bwd, fused)
            per_op = max((_time_call(f2, *a2) - _time_call(f1, *a1))
                         / (r2 - r1), 1e-9)
            res[kind][tag] = {"ms": round(per_op * 1e3, 4)}
    return res


def measure_train_glue_dispatches(hidden=32, heads=4, vocab=96, seq=16,
                                  batch=2):
    """Per-layer TRAINING-forward dispatch count of the GPT block
    chain, glue fusion off vs on (ISSUE 19) — counted exactly by the
    profiler op-hook at L=1/L=2 like ``measure_decode_dispatches``; the
    difference isolates the per-layer chain from embedding/final-norm
    constants. Forward-only by construction: the backward replays
    inside ``jax.vjp`` and never re-enters the dispatcher, so its cost
    shows up in the ``measure_glue`` scan-slope ms, not here. The
    ``glue_*`` counts are the norm/residual subset (add, layer_norm,
    rms_norm, fused_residual_norm) of the totals."""
    import paddle_tpu as pp
    from paddle_tpu.core import dispatch as _dispatch
    from paddle_tpu.core import state as _state
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.models.gpt import GPTConfig, GPTModel

    GLUE_OPS = ("add", "layer_norm", "rms_norm", "fused_residual_norm")

    def count_ops(layers, fused):
        pp.seed(0)
        cfg = GPTConfig(vocab_size=vocab, hidden_size=hidden,
                        num_layers=layers, num_heads=heads,
                        max_seq_len=seq, dropout=0.0,
                        use_flash_attention=False)
        model = GPTModel(cfg)
        model.train()
        ids = Tensor(np.zeros((batch, seq), np.int32))
        n, g = [0], [0]

        def hook(name, t0, t1):
            n[0] += 1
            if name in GLUE_OPS:
                g[0] += 1

        # flag hygiene: entry flag restored on ANY exit (the PR4
        # setup-inside-the-try rule) — a crashed count must not leave
        # glue fusion flipped for the rest of the process
        old = _state.get_flag("train_glue_fusion")
        _dispatch._profile_hook = hook
        try:
            _state.set_flags({"train_glue_fusion": fused})
            with pp.no_grad():
                model(ids)
        finally:
            _dispatch._profile_hook = None
            _state.set_flags({"train_glue_fusion": old})
        return n[0], g[0]

    u1, gu1 = count_ops(1, False)
    u2, gu2 = count_ops(2, False)
    f1, gf1 = count_ops(1, True)
    f2, gf2 = count_ops(2, True)
    out = {
        "method": "op-hook dispatch count of one eager TRAIN forward "
                  "(L=2 minus L=1 isolates the per-layer chain; "
                  "backward runs inside jax.vjp, not counted)",
        "unfused_per_layer": u2 - u1,
        "fused_per_layer": f2 - f1,
        "glue_unfused_per_layer": gu2 - gu1,
        "glue_fused_per_layer": gf2 - gf1,
    }
    _log(f"train glue dispatches/layer: {out['unfused_per_layer']} -> "
         f"{out['fused_per_layer']} (glue subset "
         f"{out['glue_unfused_per_layer']} -> "
         f"{out['glue_fused_per_layer']})")
    return out


def measure_remat_fraction(hidden=32, heads=4, vocab=96, seq=16,
                           batch=2, layers=2,
                           policy="dots_and_kernels_saveable"):
    """Recompute fraction of selective remat, as an exact program-size
    count: flattened jaxpr eqns of the captured train step with remat
    on minus off, over the forward-only eqn count — 'what share of the
    forward does the backward replay'. Uses the analyzer's
    ``jaxpr_eqn_count`` stamp (``analysis.flat_eqn_count`` recursing
    into remat sub-jaxprs), so it needs PDTPU_ANALYSIS != off; returns
    None fractions when the stamp is unavailable."""
    import paddle_tpu as pp
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    def eqns(remat, fwd_only=False):
        pp.seed(0)
        cfg = GPTConfig(vocab_size=vocab, hidden_size=hidden,
                        num_layers=layers, num_heads=heads,
                        max_seq_len=seq, dropout=0.0,
                        use_flash_attention=False)
        m = GPTForCausalLM(cfg)
        if remat:
            for blk in m.gpt.blocks:
                blk._recompute = True
                blk._recompute_policy = policy
        m.train()
        opt = pp.optimizer.SGD(learning_rate=0.01,
                               parameters=m.parameters())

        if fwd_only:
            @pp.jit.to_static(full_graph=True)
            def step(ids, labels):
                return m(ids, labels)
        else:
            @pp.jit.to_static(full_graph=True)
            def step(ids, labels):
                loss = m(ids, labels)
                loss.backward()
                opt.step()
                opt.clear_grad()
                return loss
        ids = pp.to_tensor(np.zeros((batch, seq), np.int32))
        step(ids, ids)
        exe = next(iter(step._cache.values()))
        return int(getattr(exe, "jaxpr_eqn_count", 0) or 0)

    fwd = eqns(False, fwd_only=True)
    off = eqns(False)
    on = eqns(True)
    frac = round((on - off) / fwd, 3) if fwd and off and on else None
    out = {
        "method": "flattened jaxpr eqn count of the captured train "
                  "step (analysis.flat_eqn_count), remat on minus off "
                  "over the forward-only count",
        "policy": policy,
        "fwd_eqns": fwd,
        "step_eqns": off,
        "step_eqns_remat": on,
        "recompute_fraction": frac,
    }
    _log(f"remat recompute fraction [{policy}]: {frac} "
         f"(fwd {fwd} eqns, step {off} -> {on})")
    return out


def train_batch_headroom(budget_gb=16.0, hidden=768, layers=4, heads=12,
                         vocab=1024, seq=256, batches=(1, 2, 4, 8, 16),
                         remat=None):
    """Walk doubling batch sizes against the PR16 static-peak gauge:
    capture the full train step (fwd+bwd+optimizer) at each batch size
    and read the analyzer's ``static_peak_bytes`` off the executable —
    the same number the ``hbm.static_peak_bytes{fn}`` gauge exports.
    ``remat`` (a fleet.recompute policy name) prices the selective-
    remat headroom: the largest batch whose static peak fits the
    budget is the train-batch headroom of the config. A CAPTURE-only
    walk — nothing trains; rows after the first over-budget batch are
    skipped (the peak is monotone in batch)."""
    import paddle_tpu as pp
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    budget = int(budget_gb * (1 << 30))
    rows, max_fit = [], None
    for bs in batches:
        pp.seed(0)
        cfg = GPTConfig(vocab_size=vocab, hidden_size=hidden,
                        num_layers=layers, num_heads=heads,
                        max_seq_len=seq, dropout=0.0,
                        use_flash_attention=False)
        m = GPTForCausalLM(cfg)
        if remat:
            for blk in m.gpt.blocks:
                blk._recompute = True
                blk._recompute_policy = remat
        m.train()
        opt = pp.optimizer.SGD(learning_rate=0.01,
                               parameters=m.parameters())

        @pp.jit.to_static(full_graph=True)
        def step(ids, labels):
            loss = m(ids, labels)
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss

        ids = pp.to_tensor(np.zeros((bs, seq), np.int32))
        step(ids, ids)
        exe = next(iter(step._cache.values()))
        peak = int(getattr(exe, "static_peak_bytes", 0) or 0)
        fits = bool(peak and peak <= budget)
        rows.append({"batch": bs, "static_peak_bytes": peak,
                     "fits": fits})
        _log(f"headroom: batch {bs} static peak "
             f"{peak / (1 << 20):.0f} MiB "
             f"({'fits' if fits else 'OVER'} {budget_gb} GiB)"
             + (f" [remat={remat}]" if remat else ""))
        if fits:
            max_fit = bs
        elif peak:
            break  # monotone: larger batches only get worse
    return {"budget_bytes": budget, "remat": remat,
            "max_batch_fits": max_fit, "rows": rows}


def kernel_breakdown(batch=8, seq=1024, hidden=768, heads=12, layers=12,
                     n_params=None, att=None):
    """Per-kernel fwd/bwd breakdown at the bench GPT-124M shapes —
    emitted with EVERY calibration run so the attention backward/forward
    ratio (the ISSUE-11 regression: 4.5x measured vs ~2.5x FLOP-ideal)
    is tracked as a number, alongside the norm and fused-optimizer
    kernels that ride the same step. ``att``: reuse an already-measured
    ``measure_attention`` result instead of re-sweeping. ``n_params``:
    the fused-optimizer bucket size; defaults to the calibrated model's
    transformer-block parameter count (12*L*H^2, the dominant flat
    bucket) so a tiny-config calibration times a tiny bucket instead of
    a hardcoded GPT-124M one."""
    if n_params is None:
        n_params = 12 * layers * hidden * hidden
    n_params = max(1024, -(-int(n_params) // 1024) * 1024)  # ALIGN pad
    if att is None:
        att = measure_attention(batch, heads, seq, hidden // heads)
    ratio = (att["bwd"]["ms"] / att["fwd"]["ms"]
             if att["fwd"]["ms"] else None)
    out = {
        "attention": {"fwd_ms": att["fwd"]["ms"],
                      "bwd_ms": att["bwd"]["ms"],
                      "fwd_tflops": att["fwd"]["tflops"],
                      "bwd_tflops": att["bwd"]["tflops"],
                      "per_layer": True},
        "attention_bwd_fwd_ratio": round(ratio, 2) if ratio else None,
        "attention_bwd_fwd_ratio_flop_ideal": 2.5,
        "layernorm": dict(measure_norm(batch * seq, hidden),
                          shape=[batch * seq, hidden]),
        "fused_optimizer": measure_fused_optimizer(n_params),
        # decode megakernel (ISSUE 18): exact dispatch counts per
        # decode layer, unfused vs fused — the serving-latency lever
        # the serving_bench launch_share column prices out
        "decode_dispatches": measure_decode_dispatches(),
        # training glue share (ISSUE 19): norm/residual dispatch count
        # per TRAIN layer (fused vs unfused) plus the fused-vs-unfused
        # glue chain ms, fwd and bwd — the per-step glue budget the
        # train_glue_fusion flag buys back
        "glue": dict(measure_train_glue_dispatches(),
                     **{"chain": dict(measure_glue(batch * seq, hidden),
                                      shape=[batch * seq, hidden])}),
        # selective-remat recompute share (ISSUE 19): exact program-
        # size fraction the backward replays under the default policy
        "remat": measure_remat_fraction(),
    }
    glue_ms = out["glue"]["chain"]
    _log(f"kernels: attn fwd {att['fwd']['ms']} ms / bwd "
         f"{att['bwd']['ms']} ms (ratio {out['attention_bwd_fwd_ratio']}"
         f"), ln fwd {out['layernorm']['fwd']['ms']} / bwd "
         f"{out['layernorm']['bwd']['ms']} ms, fused-opt "
         f"{out['fused_optimizer']['ms']} ms")
    _log(f"glue chain: fused fwd {glue_ms['fused']['fwd']['ms']} / bwd "
         f"{glue_ms['fused']['bwd']['ms']} ms vs unfused fwd "
         f"{glue_ms['unfused']['fwd']['ms']} / bwd "
         f"{glue_ms['unfused']['bwd']['ms']} ms; "
         f"remat recompute fraction "
         f"{out['remat']['recompute_fraction']}")
    return out


def _scanned_conv(n, h, w, cin, cout, kh, kw, stride, reps, fmt="NCHW",
                  bwd=False, dtype=jnp.bfloat16):
    """One jit program running ``reps`` convs (optionally + input/weight
    grads), index-perturbed like the matmul scan."""
    rng = np.random.default_rng(0)
    xshape = (n, cin, h, w) if fmt == "NCHW" else (n, h, w, cin)
    x = jnp.asarray(rng.normal(size=xshape) * 0.1, dtype)
    wgt = jnp.asarray(rng.normal(size=(cout, cin, kh, kw)) * 0.1, dtype)
    dn = jax.lax.conv_dimension_numbers(
        xshape, wgt.shape,
        (fmt, "OIHW", fmt))
    pad = ((kh // 2, kh // 2), (kw // 2, kw // 2))

    def conv(x, wgt):
        return jax.lax.conv_general_dilated(
            x, wgt, (stride, stride), pad, dimension_numbers=dn)

    if not bwd:
        @jax.jit
        def f(x, wgt):
            def body(c, i):
                return c + conv(x + i.astype(dtype) * 1e-6, wgt), None
            z = jnp.zeros(jax.eval_shape(conv, x, wgt).shape, dtype)
            return jax.lax.scan(body, z, jnp.arange(reps))[0]
    else:
        grad = jax.grad(
            lambda x, wgt: conv(x, wgt).astype(jnp.float32).sum(),
            argnums=(0, 1))

        @jax.jit
        def f(x, wgt):
            def body(c, i):
                # BOTH grads must feed the carry: dropping dw would let
                # XLA dead-code-eliminate the dW convolution from the
                # timed program (and conv is linear, so the forward never
                # runs in the grad program — bwd times exactly dX+dW)
                dx, dw = grad(x + i.astype(dtype) * 1e-6, wgt)
                return c + dx.astype(dtype) + dw.sum().astype(dtype), None
            return jax.lax.scan(body, jnp.zeros(xshape, dtype),
                                jnp.arange(reps))[0]

    return f, (x, wgt)


def measure_conv(n, h, w, cin, cout, kh, kw, stride=1, fmt="NCHW",
                 bwd=False, r1=None, r2=None):
    """Kernel-only conv TF/s via the two-R slope. ResNet-class convs run
    in tens of microseconds, far below the tunnel's per-dispatch jitter —
    the default rep counts auto-scale so that r2-r1 puts >= ~25 kernel-
    milliseconds between the two timed programs (estimated at 100 TF/s).
    A slope that still comes out non-positive is below timing resolution:
    the returned TF/s is None in that case, never a fabricated number."""
    ho, wo = h // stride, w // stride
    flops = 2.0 * n * ho * wo * cout * cin * kh * kw
    if bwd:
        flops *= 2.0  # dX + dW (the fwd conv is linear: not in the program)
    if r1 is None or r2 is None:
        est = flops / 100e12  # optimistic per-rep seconds
        delta = max(32, int(0.025 / max(est, 1e-7)))
        delta = min(delta, 2048)
        r1, r2 = max(4, delta // 8), max(4, delta // 8) + delta
    f1, a1 = _scanned_conv(n, h, w, cin, cout, kh, kw, stride, r1, fmt, bwd)
    f2, a2 = _scanned_conv(n, h, w, cin, cout, kh, kw, stride, r2, fmt, bwd)
    t1 = _time_call(f1, *a1)
    t2 = _time_call(f2, *a2)
    per_op = (t2 - t1) / (r2 - r1)
    if per_op <= 0:
        return None, None
    return flops / per_op / 1e12, per_op


# ResNet50 bottleneck conv inventory: (h, w, cin, cout, k, stride, count)
# per forward pass (conv1 + 4 stages; downsample convs folded into count-
# weighted equivalents; fc excluded — it is a tiny matmul).
_RESNET50_CONVS = [
    ("conv1_7x7_s2", 224, 224, 3, 64, 7, 2, 1),
    ("s1_reduce_1x1", 56, 56, 256, 64, 1, 1, 2),     # +first from 64
    ("s1_3x3", 56, 56, 64, 64, 3, 1, 3),
    ("s1_expand_1x1", 56, 56, 64, 256, 1, 1, 3),
    ("s2_reduce_1x1", 28, 28, 512, 128, 1, 1, 3),
    ("s2_3x3", 28, 28, 128, 128, 3, 1, 4),
    ("s2_expand_1x1", 28, 28, 128, 512, 1, 1, 4),
    ("s3_reduce_1x1", 14, 14, 1024, 256, 1, 1, 5),
    ("s3_3x3", 14, 14, 256, 256, 3, 1, 6),
    ("s3_expand_1x1", 14, 14, 256, 1024, 1, 1, 6),
    ("s4_reduce_1x1", 7, 7, 2048, 512, 1, 1, 2),
    ("s4_3x3", 7, 7, 512, 512, 3, 1, 3),
    ("s4_expand_1x1", 7, 7, 512, 2048, 1, 1, 3),
]


def calibrate_resnet50(batch=32, fmts=("NCHW", "NHWC"), shapes=None):
    """Conv roofline for the ResNet50 north-star config: measured TF/s for
    the distinct conv shapes (fwd and fwd+bwd), in both layouts, plus the
    count-weighted step-time lower bound per layout. Answers whether the
    b32/224^2 shapes underfill the MXU and whether the layout handed to
    XLA matters. ``shapes``: optional subset of _RESNET50_CONVS names —
    each (shape, layout, direction) costs two compiles over the remote
    compiler, so the full 13-shape sweep is ~10 minutes."""
    convs = [c for c in _RESNET50_CONVS
             if shapes is None or c[0] in shapes]
    out = {"device": str(jax.devices()[0].device_kind), "batch": batch,
           "method": "scan-slope (see module docstring)", "convs": {},
           "roofline": {}}
    for fmt in fmts:
        total = 0.0
        total_flops = 0.0
        unresolved = 0
        for name, h, w, cin, cout, k, s, cnt in convs:
            tf_f, dt_f = measure_conv(batch, h, w, cin, cout, k, k, s, fmt)
            tf_b, dt_b = measure_conv(batch, h, w, cin, cout, k, k, s, fmt,
                                      bwd=True)
            rec = out["convs"].setdefault(name, {
                "shape": [batch, h, w, cin, cout, k, s], "count": cnt})
            rec[fmt] = {
                "fwd_tflops": round(tf_f, 2) if tf_f else None,
                "bwd_tflops": round(tf_b, 2) if tf_b else None,
                "fwd_ms": round(dt_f * 1e3, 3) if dt_f else None,
                "bwd_ms": round(dt_b * 1e3, 3) if dt_b else None}
            _log(f"{fmt} {name}: fwd {tf_f and round(tf_f, 1)} / "
                 f"bwd {tf_b and round(tf_b, 1)} TF/s")
            if dt_f and dt_b:
                total += cnt * (dt_f + dt_b)
                total_flops += cnt * 3 * 2.0 * batch * (h // s) * (w // s) \
                    * cout * cin * k * k
            else:
                unresolved += 1
        out["roofline"][fmt] = {
            "conv_time_ms": round(total * 1e3, 2),
            "blended_conv_tflops": round(total_flops / total / 1e12, 2)
            if total else None,
            "unresolved_shapes": unresolved,
            "note": ("lower bound: conv kernel time only — BN/ReLU/pool/"
                     "optimizer ride free; real step time must exceed it; "
                     "shapes below timing resolution excluded"),
        }
    return out


def calibrate(batch=8, seq=1024, hidden=768, heads=12, layers=12,
              vocab=50304, ffn_mult=4):
    """Roofline for the bench GPT-124M config at (batch, seq)."""
    tokens = batch * seq
    head_dim = hidden // heads

    gemms = {
        # name: (m, k, n, count per step)
        "qkv": (tokens, hidden, 3 * hidden, layers),
        "attn_proj": (tokens, hidden, hidden, layers),
        "ffn_up": (tokens, hidden, ffn_mult * hidden, layers),
        "ffn_down": (tokens, ffn_mult * hidden, hidden, layers),
        "lm_head": (tokens, hidden, vocab, 1),
    }

    out = {"device": str(jax.devices()[0].device_kind),
           "batch": batch, "seq": seq,
           "method": "scan-slope (see module docstring)", "gemms": {}}

    for s in (8192,):
        tf, dt = measure_matmul(s, s, s)
        out["gemms"][f"square_{s}"] = {
            "shape": [s, s, s], "tflops": round(tf, 2),
            "ms": round(dt * 1e3, 3)}
        _log(f"square_{s}: {tf:.1f} TF/s ({dt*1e3:.3f} ms)")

    total_matmul_time = 0.0
    total_matmul_flops = 0.0
    for name, (m, k, n, cnt) in gemms.items():
        tf, dt = measure_matmul(m, k, n)
        tf_dx, dt_dx = measure_matmul(m, n, k)      # dX = dY @ W^T
        tf_dw, dt_dw = measure_matmul(k, m, n)      # dW = X^T @ dY
        out["gemms"][name] = {
            "shape": [m, k, n], "count": cnt,
            "fwd_tflops": round(tf, 2), "dx_tflops": round(tf_dx, 2),
            "dw_tflops": round(tf_dw, 2),
            "fwd_ms": round(dt * 1e3, 3)}
        _log(f"{name}: fwd {tf:.1f} / dx {tf_dx:.1f} / dw {tf_dw:.1f} TF/s")
        total_matmul_time += cnt * (dt + dt_dx + dt_dw)
        total_matmul_flops += cnt * 3 * (2.0 * m * k * n)

    att = measure_attention(batch, heads, seq, head_dim)
    out["attention"] = dict(att, shape=[batch, heads, seq, head_dim],
                            causal=True)
    _log(f"attention: fwd {att['fwd']['tflops']} TF/s "
         f"({att['fwd']['ms']} ms), bwd {att['bwd']['tflops']} TF/s "
         f"({att['bwd']['ms']} ms)")
    att_time = layers * (att["fwd"]["ms"] + att["bwd"]["ms"]) / 1e3

    # per-kernel fwd/bwd breakdown (ISSUE 11): the backward-ratio
    # regression is tracked in every calibration run
    out["kernels"] = kernel_breakdown(batch, seq, hidden, heads, layers,
                                      att=att)

    step_lb = total_matmul_time + att_time
    out["roofline"] = {
        "matmul_time_ms": round(total_matmul_time * 1e3, 2),
        "attention_time_ms": round(att_time * 1e3, 2),
        "step_time_lower_bound_ms": round(step_lb * 1e3, 2),
        "blended_matmul_tflops": round(
            total_matmul_flops / total_matmul_time / 1e12, 2),
        "note": ("lower bound: GEMM+attention kernel time only, zero "
                 "elementwise/softmax/optimizer/dispatch; real step time "
                 "must exceed this"),
    }
    return out


if __name__ == "__main__":
    res = calibrate()
    print(json.dumps(res, indent=2))
    if len(sys.argv) > 1:
        with open(sys.argv[1], "w") as f:
            json.dump(res, f, indent=2)

"""Scale-5 validation: AOT-lower the GPT-13B GSPMD train step on a
32-device virtual mesh and check the per-device memory fits v5e HBM.

The reference's scale-5 milestone trains GPT-13B on 4 nodes
(BASELINE.md milestone 5; reference
``test/auto_parallel/hybrid_strategy/semi_auto_llama.py`` is the shape
of its validation). Real chips are not needed to validate the SPMD
program: ``jax.jit(...).lower(avals).compile()`` builds the full
partitioned executable from ShapeDtypeStructs — no weights are ever
materialized.

The train step here is the same program our jit capture produces for
``GPTForCausalLM`` + ``shard_gpt`` (Megatron TP specs: column-parallel
qkv/fc1, row-parallel proj/fc2, vocab-parallel embedding; bf16 compute
with fp32 master weights and AdamW; dots_saveable remat), written
directly over stacked per-layer params with ``lax.scan`` so the 40-layer
HLO stays compact — ``check_tiny_equivalence()`` proves it numerically
against the framework model class at a small config.

Sharding plan on mesh (dp=4, mp=8):
- weights: TP over mp (as shard_gpt); replicated over dp
- AdamW m/v + fp32 master: additionally sharded over dp (ZeRO-1)
- activations: batch over dp; sequence-major intermediates stay sharded
  by GSPMD propagation
"""
from __future__ import annotations

import math
from dataclasses import dataclass
from functools import partial

import numpy as np

V5E_HBM = 16 * 1024 ** 3


@dataclass
class Cfg:
    vocab_size: int = 50304
    hidden_size: int = 5120
    num_layers: int = 40
    num_heads: int = 40
    seq_len: int = 2048
    batch: int = 32          # global batch (per step, per 32-chip slice)

    @property
    def head_dim(self):
        return self.hidden_size // self.num_heads

    @property
    def ffn(self):
        return 4 * self.hidden_size

    def n_params(self):
        h, L, v = self.hidden_size, self.num_layers, self.vocab_size
        return v * h + L * (4 * h * h + 2 * h * 4 * h + 3 * h
                            + 4 * h + 2 * h) + h


def param_specs(cfg, jnp, P):
    """(aval, weight_pspec, optstate_pspec) per param. Weight specs are
    the shard_gpt rules (models/gpt.py:314); opt-state specs add dp
    (ZeRO-1)."""
    h, L, v, f = (cfg.hidden_size, cfg.num_layers, cfg.vocab_size,
                  cfg.ffn)
    out = {
        # name: (shape, weight spec, opt spec)
        "wte":  ((v, h), P("mp", None), P("mp", "dp")),
        "qkv_w": ((L, h, 3 * h), P(None, None, "mp"),
                  P(None, "dp", "mp")),
        "qkv_b": ((L, 3 * h), P(None, "mp"), P(None, "mp")),
        "proj_w": ((L, h, h), P(None, "mp", None),
                   P(None, "mp", "dp")),
        "proj_b": ((L, h), P(None, None), P(None, "dp")),
        "fc1_w": ((L, h, f), P(None, None, "mp"), P(None, "dp", "mp")),
        "fc1_b": ((L, f), P(None, "mp"), P(None, "mp")),
        "fc2_w": ((L, f, h), P(None, "mp", None), P(None, "mp", "dp")),
        "fc2_b": ((L, h), P(None, None), P(None, "dp")),
        "ln1_w": ((L, h), P(None, None), P(None, "dp")),
        "ln1_b": ((L, h), P(None, None), P(None, "dp")),
        "ln2_w": ((L, h), P(None, None), P(None, "dp")),
        "ln2_b": ((L, h), P(None, None), P(None, "dp")),
        "lnf_w": ((h,), P(None), P("dp")),
        "lnf_b": ((h,), P(None), P("dp")),
    }
    return out


def _ln(x, w, b, jnp):
    m = x.mean(-1, keepdims=True)
    v = ((x - m) ** 2).mean(-1, keepdims=True)
    return (x - m) * (1.0 / jnp.sqrt(v + 1e-5)) * w + b


def make_train_step(cfg, use_flash=True):
    import jax
    import jax.numpy as jnp
    from jax import lax

    H, nh, hd = cfg.hidden_size, cfg.num_heads, cfg.head_dim

    def attention(x_bf16):
        # [B, S, H] -> causal MHA; flash kernel on TPU, dot fallback on
        # CPU (the virtual-mesh AOT path)
        B, S, _ = x_bf16.shape
        q, k, v = jnp.split(x_bf16, 3, axis=-1)
        q = q.reshape(B, S, nh, hd)
        k = k.reshape(B, S, nh, hd)
        v = v.reshape(B, S, nh, hd)
        if use_flash:
            from paddle_tpu.ops.pallas.flash_attention import (
                flash_attention,
            )
            o = flash_attention(q, k, v, causal=True)
        else:
            scores = jnp.einsum("bsnd,btnd->bnst", q, k) / math.sqrt(hd)
            mask = jnp.tril(jnp.ones((S, S), bool))
            scores = jnp.where(mask, scores, -1e9)
            o = jnp.einsum("bnst,btnd->bsnd",
                           jax.nn.softmax(scores, axis=-1), v)
        return o.reshape(B, S, H)

    def block(h, layer):
        (qkv_w, qkv_b, proj_w, proj_b, fc1_w, fc1_b, fc2_w, fc2_b,
         ln1_w, ln1_b, ln2_w, ln2_b) = layer
        y = _ln(h, ln1_w, ln1_b, jnp).astype(jnp.bfloat16)
        y = y @ qkv_w.astype(jnp.bfloat16) + qkv_b.astype(jnp.bfloat16)
        y = attention(y)
        y = y @ proj_w.astype(jnp.bfloat16) + proj_b.astype(jnp.bfloat16)
        h = h + y.astype(h.dtype)
        y = _ln(h, ln2_w, ln2_b, jnp).astype(jnp.bfloat16)
        y = jax.nn.gelu(y @ fc1_w.astype(jnp.bfloat16)
                        + fc1_b.astype(jnp.bfloat16), approximate=True)
        y = y @ fc2_w.astype(jnp.bfloat16) + fc2_b.astype(jnp.bfloat16)
        return h + y.astype(h.dtype)

    layer_keys = ["qkv_w", "qkv_b", "proj_w", "proj_b", "fc1_w",
                  "fc1_b", "fc2_w", "fc2_b", "ln1_w", "ln1_b", "ln2_w",
                  "ln2_b"]

    def forward_loss(params, ids, labels):
        x = jnp.take(params["wte"], ids, axis=0).astype(jnp.float32)
        pos = jnp.arange(ids.shape[1])
        # learned positions folded into wte row 0..S for compactness is
        # NOT the real model; use sinusoidal-free: the framework model
        # uses a wpe table — omitted here (it is 0.08% of params and
        # does not change the memory picture); equivalence check runs
        # with wpe zeroed
        del pos

        def body(h, layer):
            # dots_saveable: keep matmul outputs, recompute elementwise
            return jax.checkpoint(
                block, policy=jax.checkpoint_policies.dots_saveable)(
                    h, layer), None

        layers = tuple(params[k] for k in layer_keys)
        x, _ = lax.scan(body, x, layers)
        x = _ln(x, params["lnf_w"], params["lnf_b"], jnp)
        logits = (x.astype(jnp.bfloat16)
                  @ params["wte"].T.astype(jnp.bfloat16))
        logits = logits.astype(jnp.float32)
        lse = jax.nn.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(
            logits, labels[..., None], axis=-1)[..., 0]
        return (lse - gold).mean()

    def train_step(params, m, v, t, ids, labels):
        loss, grads = jax.value_and_grad(forward_loss)(
            params, ids, labels)
        lr, b1, b2, eps = 1e-4, 0.9, 0.95, 1e-8
        t = t + 1
        new_p, new_m, new_v = {}, {}, {}
        for k in params:
            g = grads[k]
            new_m[k] = b1 * m[k] + (1 - b1) * g
            new_v[k] = b2 * v[k] + (1 - b2) * g * g
            mhat = new_m[k] / (1 - b1 ** t)
            vhat = new_v[k] / (1 - b2 ** t)
            new_p[k] = params[k] - lr * mhat / (jnp.sqrt(vhat) + eps)
        return loss, new_p, new_m, new_v, t

    return train_step


def lower_13b(n_devices=32, dp=4, mp=8, cfg=None, compile_=True):
    """AOT-lower (and optionally compile) the 13B train step; returns
    (lowered_or_compiled, per_device_bytes or None)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    cfg = cfg or Cfg()
    devs = np.array(jax.devices()[:n_devices]).reshape(dp, mp)
    mesh = Mesh(devs, ("dp", "mp"))
    specs = param_specs(cfg, jnp, P)

    def sds(shape, dtype, spec):
        return jax.ShapeDtypeStruct(shape, dtype,
                                    sharding=NamedSharding(mesh, spec))

    params = {k: sds(s, jnp.bfloat16, wspec)
              for k, (s, wspec, _) in specs.items()}
    m_av = {k: sds(s, jnp.float32, ospec)
            for k, (s, _, ospec) in specs.items()}
    v_av = {k: sds(s, jnp.float32, ospec)
            for k, (s, _, ospec) in specs.items()}
    t_av = jax.ShapeDtypeStruct((), jnp.int32)
    ids = sds((cfg.batch, cfg.seq_len), jnp.int32, P("dp", None))
    labels = sds((cfg.batch, cfg.seq_len), jnp.int32, P("dp", None))

    step = make_train_step(cfg, use_flash=False)
    # donate params/opt state: the real executable updates them in place
    # (the jit _Executable donates state buffers the same way)
    lowered = jax.jit(step, donate_argnums=(0, 1, 2, 3)).lower(
        params, m_av, v_av, t_av, ids, labels)
    if not compile_:
        return lowered, None
    compiled = lowered.compile()
    mem = compiled.memory_analysis()
    resident = None
    if mem:
        # peak_memory accounts for buffer liveness/reuse (temp_size is
        # the sum of every allocation and wildly overstates); arguments
        # are resident alongside the temps until their last use
        resident = mem.peak_memory_in_bytes + mem.argument_size_in_bytes
    return compiled, resident


def check_tiny_equivalence():
    """Prove the harness computes the same loss as the framework model
    class (GPTForCausalLM) at a small config — the pure program IS the
    model, so the 13B lowering validates the real architecture."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    gcfg = GPTConfig(vocab_size=97, hidden_size=64, num_layers=2,
                     num_heads=4, max_seq_len=32, dropout=0.0)
    paddle.seed(0)
    model = GPTForCausalLM(gcfg)
    model.eval()
    # zero the position table: the harness has no wpe
    model.gpt.wpe.weight._data = jnp.zeros_like(
        model.gpt.wpe.weight._read())

    cfg = Cfg(vocab_size=97, hidden_size=64, num_layers=2, num_heads=4,
              seq_len=16, batch=2)
    step = make_train_step(cfg, use_flash=False)

    blocks = model.gpt.blocks
    params = {
        "wte": model.gpt.wte.weight._read().astype(jnp.bfloat16),
        "lnf_w": model.gpt.ln_f.weight._read().astype(jnp.bfloat16),
        "lnf_b": model.gpt.ln_f.bias._read().astype(jnp.bfloat16),
    }

    def stack(getter):
        return jnp.stack([getter(b) for b in blocks]).astype(jnp.bfloat16)

    params.update({
        "qkv_w": stack(lambda b: b.attn.qkv.weight._read()),
        "qkv_b": stack(lambda b: b.attn.qkv.bias._read()),
        "proj_w": stack(lambda b: b.attn.proj.weight._read()),
        "proj_b": stack(lambda b: b.attn.proj.bias._read()),
        "fc1_w": stack(lambda b: b.mlp.fc1.weight._read()),
        "fc1_b": stack(lambda b: b.mlp.fc1.bias._read()),
        "fc2_w": stack(lambda b: b.mlp.fc2.weight._read()),
        "fc2_b": stack(lambda b: b.mlp.fc2.bias._read()),
        "ln1_w": stack(lambda b: b.ln1.weight._read()),
        "ln1_b": stack(lambda b: b.ln1.bias._read()),
        "ln2_w": stack(lambda b: b.ln2.weight._read()),
        "ln2_b": stack(lambda b: b.ln2.bias._read()),
    })

    rng = np.random.default_rng(0)
    ids = rng.integers(0, 97, (2, 16)).astype(np.int32)
    labels = rng.integers(0, 97, (2, 16)).astype(np.int32)

    zeros = {k: jnp.zeros_like(v, jnp.float32)
             for k, v in params.items()}
    loss, *_ = jax.jit(step)(params, zeros, zeros,
                             jnp.int32(0), ids, labels)

    ref = float(model(paddle.to_tensor(ids), paddle.to_tensor(labels)))
    return float(loss), ref


if __name__ == "__main__":
    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=32")
    import jax
    jax.config.update("jax_platforms", "cpu")

    got, ref = check_tiny_equivalence()
    print(f"tiny equivalence: harness={got:.4f} model={ref:.4f}")
    print(f"13B params: {Cfg().n_params() / 1e9:.2f}B")
    assert abs(got - ref) < 0.05, "harness != framework model"

    compiled, resident = lower_13b()
    print(f"13B lowered+compiled on 32 virtual devices; "
          f"per-device resident ~{resident / 1024**3:.2f} GiB "
          f"(v5e HBM {V5E_HBM / 1024**3:.0f} GiB)")
    assert resident is not None and resident < V5E_HBM, \
        f"13B step does not fit v5e HBM: {resident}"
    print("AOT 13B OK")

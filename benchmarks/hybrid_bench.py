#!/usr/bin/env python
"""gpt_3d bench row: hybrid DP x TP x PP training over the fleet
topology (ISSUE 11).

The row answers two questions the single-chip gpt124m headline cannot:

1. does the hybrid path SCALE — tokens/sec on the full mesh vs the
   1-device step rate times the device count (target >= 0.9x linear to
   4 chips);
2. is the communication HIDDEN — ``overlap_frac`` from the
   overlap-scheduled bucketed DP grad sync (distributed/overlap.py) and
   the pipeline's eager-issued ppermute sends (pp_overlap_p2p), with
   ``comm_ms`` alongside so a regression shows up as a number, not a
   vibe.

Layout: ``HybridCommunicateGroup(dp, pp, mp)`` -> ``process_mesh()`` ->
``GPTForCausalLMPipe.train_batch`` (fused 1F1B, dp via batch_axes, TP
via the stacked-leaf tp_rules) compiled as ONE jit step. The overlap
telemetry comes from an eager replicated-DP segment over the same
device set — the path the scheduler exists for (in-program GSPMD comm
is XLA-scheduled and unobservable from the host).

CPU smoke (tests/test_overlap.py): tiny config, dp2 x pp2 on the forced
8-device mesh, validates the row's accounting fields and the bitwise
gates; absolute times and the >= 0.9x scaling gate are TPU-only claims.
TP inside the pipeline needs partial-auto shard_map (jax >= 0.5) — on
older jax the row demotes mp into dp and records the demotion.
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np


def _log(msg):
    print(msg, file=sys.stderr, flush=True)


def _measure_gpt_3d(cfg, dp=2, pp=2, mp=1, batch_per_dp=2, seq=64,
                    num_microbatches=2, steps=8, warmup=2,
                    overlap_steps=3, lr=1e-4, peak_flops=None):
    """One gpt_3d row. ``cfg``: GPTConfig (dropout must be 0). Batch is
    ``batch_per_dp * dp`` so per-device work is constant as dp grows —
    the weak-scaling convention the linearity gate assumes."""
    import jax

    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    from paddle_tpu.core import state as _state
    from paddle_tpu.distributed.fleet.topology import \
        HybridCommunicateGroup
    from paddle_tpu.models.gpt import GPTForCausalLM, GPTForCausalLMPipe

    need = dp * pp * mp
    ndev = len(jax.devices())
    if ndev < need:
        raise RuntimeError(f"gpt_3d wants {need} devices, have {ndev}")
    tp_axis = "mp" if mp > 1 else None
    demoted = False
    from paddle_tpu.core.meshutil import legacy_manual_vjp
    if tp_axis and legacy_manual_vjp():
        # partial-auto shard_map (TP under GSPMD inside the manual
        # pipeline) does not exist before jax 0.5 — fold mp into dp so
        # the row still measures the full device set
        dp, mp, tp_axis, demoted = dp * mp, 1, None, True
    hcg = HybridCommunicateGroup(dp_degree=dp, pp_degree=pp,
                                 mp_degree=mp)
    mesh = hcg.process_mesh()
    batch = batch_per_dp * dp

    # compile accounting baseline: train.compile_ms is process-global
    # (every _Executable.build in the process feeds it — earlier bench
    # rows included), so the row reports the DELTA over its own run
    from paddle_tpu.observability import metrics as _om
    _comp_h = _om.registry().histogram(
        "train.compile_ms",
        "trace+lower wall time of captured programs",
        _om.LATENCY_BUCKETS_MS)
    comp0 = (_comp_h.count, _comp_h.sum)

    paddle.seed(0)
    pipe = GPTForCausalLMPipe(cfg, mesh, pp_axis="pp", dp_axis="dp",
                              num_microbatches=num_microbatches,
                              tp_axis=tp_axis)
    pipe.train()
    opt = paddle.optimizer.AdamW(learning_rate=lr,
                                 parameters=pipe.parameters())

    @paddle.jit.to_static
    def step(ids, labels):
        loss = pipe.train_batch(ids, labels)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    rng = np.random.default_rng(0)

    def batch_fn():
        ids = rng.integers(0, cfg.vocab_size, (batch, seq)).astype(
            np.int32)
        lab = rng.integers(0, cfg.vocab_size, (batch, seq)).astype(
            np.int32)
        return paddle.to_tensor(ids), paddle.to_tensor(lab)

    for _ in range(warmup):
        loss = step(*batch_fn())
    float(loss)
    # feed train.step_ms the same steps the row times — into a PRIVATE
    # registry (a fit/bench run earlier in the process would pollute
    # the global one's cumulative histogram); the aggregator reads it
    # through fleet_snapshot(registry=...)
    from paddle_tpu.observability import StepTimer
    from paddle_tpu.observability.metrics import Registry as _Registry
    _row_reg = _Registry("gpt_3d_row")
    st = StepTimer(registry=_row_reg)
    st.mark()
    input_s = 0.0
    t0 = time.perf_counter()
    for _ in range(steps):
        # input_wait_ms column (ISSUE 19): the host-side batch build +
        # staging time inside the step loop — the share an async
        # double-buffered feed (Model.fit train_prefetch) would hide
        # under device compute. This manual loop stages synchronously,
        # so the column is the full stage cost.
        ti = time.perf_counter()
        ids_t, lab_t = batch_fn()
        input_s += time.perf_counter() - ti
        loss = step(ids_t, lab_t)
        st.step(tokens=batch * seq)
    final_loss = float(loss)  # sync
    dt = (time.perf_counter() - t0) / steps
    tok_s = batch * seq / dt
    # static peak of the captured 3D train step (PR16 analyzer gauge,
    # stamped at capture) — the HBM headroom column remat prices out
    static_peak = max(
        (int(getattr(e, "static_peak_bytes", 0) or 0)
         for e in getattr(step, "_cache", {}).values()), default=0)

    # --- 1-device baseline at the SAME per-device batch (weak scaling)
    paddle.seed(0)
    ref = GPTForCausalLM(cfg)
    ref.train()
    ref_opt = paddle.optimizer.AdamW(learning_rate=lr,
                                     parameters=ref.parameters())

    @paddle.jit.to_static
    def ref_step(ids, labels):
        loss = ref(ids, labels)
        loss.backward()
        ref_opt.step()
        ref_opt.clear_grad()
        return loss

    def ref_batch():
        ids = rng.integers(0, cfg.vocab_size,
                           (batch_per_dp, seq)).astype(np.int32)
        lab = rng.integers(0, cfg.vocab_size,
                           (batch_per_dp, seq)).astype(np.int32)
        return paddle.to_tensor(ids), paddle.to_tensor(lab)

    for _ in range(warmup):
        rl = ref_step(*ref_batch())
    float(rl)
    t0 = time.perf_counter()
    for _ in range(steps):
        rl = ref_step(*ref_batch())
    float(rl)
    dt1 = (time.perf_counter() - t0) / steps
    tok_s_1dev = batch_per_dp * seq / dt1
    chips = dp * pp * mp
    scaling_x = tok_s / (tok_s_1dev * chips) if tok_s_1dev else 0.0

    # --- overlap telemetry: eager replicated-DP segment over the same
    # device set, overlap scheduler ON (the in-program pipeline comm is
    # XLA-scheduled; this is the host-observable half of the claim)
    old_flag = _state.get_flag("dp_overlap_grad_sync")
    _state.set_flags({"dp_overlap_grad_sync": True})
    try:
        paddle.seed(0)
        dp_model = dist.DataParallel(GPTForCausalLM(cfg))
        dp_opt = paddle.optimizer.AdamW(
            learning_rate=lr, parameters=dp_model.parameters())
        ids, lab = batch_fn()
        for _ in range(overlap_steps):
            loss = dp_model(ids, lab)
            loss.backward()
            dp_model.apply_collective_grads()
            dp_opt.step()
            dp_opt.clear_grad()
        ov = dict(dp_model._overlap.last) if dp_model._overlap else {}
        ov.pop("ready_order", None)
        ov["collectives"] = getattr(dp_model, "_last_sync_collectives",
                                    0)
    finally:
        _state.set_flags({"dp_overlap_grad_sync": old_flag})

    # --- fleet columns (ISSUE 12): compile time + per-rank skew from
    # the aggregator.  A single-controller host is one rank, so the
    # local fleet_snapshot over the row's private registry degenerates
    # to {rank: this row's metrics}; multi-host launches pass the
    # launcher's TCP store + world_size and these same columns show the
    # straggler.  compile_ms is the delta of the process-global
    # train.compile_ms over THIS row's captures (step, ref_step,
    # overlap segment).
    from paddle_tpu.observability import aggregate as _agg
    _row_reg.gauge("train.overlap_frac").set(
        float(ov.get("overlap_frac", 0.0)))
    fleet = _agg.fleet_snapshot(registry=_row_reg)
    skew = fleet.get("skew", {}) if fleet else {}
    rank_skew = {
        "step_ms_p50": skew.get("p50_ms", {}),
        "step_ms_spread_ms": skew.get("p50_spread_ms", 0.0),
        "slowest_rank": skew.get("slowest_rank"),
        "slowest_phase": skew.get("slowest_phase"),
        "overlap_frac": skew.get("overlap_frac", {}),
        "ranks_missing": fleet.get("missing", []) if fleet else [],
    }
    comp_cnt = _comp_h.count - comp0[0]
    comp_sum = _comp_h.sum - comp0[1]

    flops_tok = ref.flops_per_token(seq)
    achieved = tok_s * flops_tok
    row = {
        "metric": "gpt_3d_train_tokens_per_sec",
        "value": round(tok_s, 1),
        "unit": "tokens/sec",
        "topology": {"dp": dp, "pp": pp, "mp": mp,
                     "tp_demoted_to_dp": demoted,
                     "num_microbatches": num_microbatches},
        "chips": chips,
        "batch": batch, "seq_len": seq,
        "step_time_ms": round(dt * 1e3, 2),
        "input_wait_ms": round(input_s / steps * 1e3, 3),
        "static_peak_bytes": static_peak,
        "tokens_per_sec_1dev": round(tok_s_1dev, 1),
        "scaling_x": round(scaling_x, 3),
        "overlap": ov,
        "pp_overlap_p2p": bool(_state.get_flag("pp_overlap_p2p")),
        "compile_ms": {"count": int(comp_cnt),
                       "total": round(float(comp_sum), 1),
                       "mean": round(comp_sum / comp_cnt, 1)
                       if comp_cnt else 0.0},
        "rank_skew": rank_skew,
        "final_loss": round(final_loss, 4),
    }
    if peak_flops:
        row["mfu"] = round(achieved / (peak_flops * chips), 4)
        row["model_tflops_per_sec"] = round(achieved / 1e12, 2)
    return row


def measure_recovery(world=2, num_iters=12, snapshot_every=3,
                     death_at=6):
    """Elastic recovery column (ISSUE 15): time-to-resume after an
    injected ``rank_dead`` plus the buddy-snapshot overhead at cadence
    ``snapshot_every``.  The rig is the host-side FleetSupervisor drill
    (thread ranks over a loopback TCPStore — the same fabric a real
    fleet's detector/snapshot/recovery path runs on; the device only
    executes the train step), so the column measures the recovery
    machinery itself on any platform: heartbeat-expiry detection, the
    coded collective timeout, buddy restore and data fast-forward."""
    import socket
    import threading

    import paddle_tpu as paddle
    from paddle_tpu.distributed.store import TCPStore
    from paddle_tpu.observability import metrics as om
    from paddle_tpu.resilience import FleetSupervisor, faults

    class _Reg(paddle.io.Dataset):
        def __init__(self, n=256):
            rng = np.random.default_rng(0)
            self.x = rng.normal(size=(n, 16)).astype("float32")
            self.y = (self.x @ np.arange(1, 17, dtype="float32")[:, None]
                      ).astype("float32")

        def __len__(self):
            return len(self.x)

        def __getitem__(self, i):
            return self.x[i], self.y[i]

    def make_model():
        paddle.seed(0)
        net = paddle.nn.Linear(16, 1)
        m = paddle.Model(net)
        m.prepare(paddle.optimizer.SGD(parameters=net.parameters(),
                                       learning_rate=0.01),
                  paddle.nn.MSELoss())
        return m

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    host = TCPStore("127.0.0.1", port, is_master=True)
    reg = om.registry()
    snap_h = reg.histogram("elastic.snapshot_ms")
    snap0 = (snap_h.count, snap_h.sum)
    data = _Reg()
    models = [make_model() for _ in range(world)]
    sups, results = [], {}
    faults.clear()
    faults.inject("rank_dead", str(world - 1), 1, death_at)
    try:
        for r in range(world):
            sups.append(FleetSupervisor(
                "127.0.0.1", port, f"rank{r}", world,
                is_master=(r == 0), snapshot_every=snapshot_every,
                collective_timeout_ms=2500.0,
                heartbeat_interval=0.25, heartbeat_timeout=2.5,
                recovery_timeout_s=45.0))

        def worker(r):
            results[r] = sups[r].fit(models[r], data, batch_size=4,
                                     num_iters=num_iters, verbose=0)
        t0 = time.perf_counter()
        ts = [threading.Thread(target=worker, args=(r,), daemon=True)
              for r in range(world)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(120)
        wall_s = time.perf_counter() - t0
    finally:
        faults.clear()
        for sup in sups:
            sup.close()
        host.close()
    lr = next((s.last_recovery for s in sups
               if s.last_recovery is not None), None)
    snaps = snap_h.count - snap0[0]
    return {
        "world": world,
        "snapshot_every": snapshot_every,
        "death_at_step": death_at,
        "recovered": lr is not None,
        "restore_source": lr["source"] if lr else None,
        "restored_step": lr["step"] if lr else None,
        # membership-change -> training-resumable (the supervisor's
        # elastic.recovery_ms for THIS recovery)
        "recovery_ms": round(lr["ms"], 1) if lr else None,
        # async capture->replicated wall per snapshot generation
        "snapshot_ms_mean": round((snap_h.sum - snap0[1]) / snaps, 2)
        if snaps else 0.0,
        "snapshots": int(snaps),
        "drill_wall_s": round(wall_s, 1),
        "completed": all(results.get(r) is True
                         for r in range(world - 1)),
    }


def bench_row(peak_flops=None, smoke=False):
    """The driver-facing row. ``smoke`` (CPU): tiny config, dp2 x pp2
    (x mp2 when partial-auto shard_map exists), accounting-only."""
    from paddle_tpu.models.gpt import GPTConfig

    if smoke:
        cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=4,
                        num_heads=4, max_seq_len=64, dropout=0.0)
        row = _measure_gpt_3d(cfg, dp=2, pp=2, mp=2, batch_per_dp=2,
                              seq=16, num_microbatches=2, steps=2,
                              warmup=1, overlap_steps=2)
        row["recovery"] = measure_recovery()
        return row
    cfg = GPTConfig(vocab_size=50304, hidden_size=768, num_layers=12,
                    num_heads=12, max_seq_len=1024, dropout=0.0,
                    recompute=False)
    import jax
    ndev = len(jax.devices())
    # 4-chip target: dp2 x pp2 with TP folded in on >= 8 chips
    dp = 2 if ndev >= 4 else 1
    mp = 2 if ndev >= 8 else 1
    pp = 2 if ndev >= 4 else max(1, ndev)
    row = _measure_gpt_3d(cfg, dp=dp, pp=pp, mp=mp, batch_per_dp=8,
                          seq=1024, num_microbatches=8, steps=10,
                          warmup=2, peak_flops=peak_flops)
    # elastic recovery column (ISSUE 15): host-side drill — the
    # detector/snapshot/restore fabric under measurement is identical
    # on TPU pods; only the train step itself is device-bound
    row["recovery"] = measure_recovery()
    return row


FILES = ["benchmarks/hybrid_bench.py",
         "paddle_tpu/distributed/fleet/pipeline.py",
         "paddle_tpu/distributed/fleet/topology.py",
         "paddle_tpu/distributed/overlap.py",
         "paddle_tpu/distributed/parallel.py",
         "paddle_tpu/distributed/collective.py",
         "paddle_tpu/core/meshutil.py",
         "paddle_tpu/ops/pallas/flash_attention.py",
         # glue-fusion kernels + recompute policies sit inside the 3D
         # step's blocks (ISSUE 19): their code re-measures the row
         "paddle_tpu/ops/pallas/fused_residual_norm.py",
         "paddle_tpu/distributed/fleet/recompute.py",
         "paddle_tpu/models/gpt.py",
         # the gpt_3d skew/compile_ms columns come from the aggregator
         # (ISSUE 12): its merge/quantile math re-measures the row
         "paddle_tpu/observability/aggregate.py",
         "paddle_tpu/observability/tracing.py",
         # the recovery column (ISSUE 15) re-measures when the elastic
         # supervisor or the membership detector changes
         "paddle_tpu/resilience/elastic_train.py",
         "paddle_tpu/distributed/elastic.py"]


def main():
    import jax

    dev = jax.devices()[0]
    if len(jax.devices()) < 4:
        print("hybrid_bench: needs >= 4 devices; skipping",
              file=sys.stderr)
        return 0
    sys.path.insert(0, __file__.rsplit("/", 1)[0])
    import measured_cache as mc
    kind = str(getattr(dev, "device_kind", dev.platform))
    ver = mc.code_version(*FILES)
    row = mc.load(kind, "gpt_3d", ver)
    if row is None:
        row = bench_row(smoke=(dev.platform != "tpu"))
        mc.store(kind, "gpt_3d", ver, row)
    print(json.dumps({"gpt_3d": row}, indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Attention implementation/block-size sweep at the bench shape, using
the dispatch-free scan-slope method (see calibrate.py). Prints a ranked
table; argv[1] = optional JSON output path."""
from __future__ import annotations

import functools
import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np


def _log(m):
    print(m, file=sys.stderr, flush=True)


def _sync(x):
    while isinstance(x, (tuple, list)):
        x = x[0]
    return float(jnp.asarray(x).reshape(-1)[0].astype(jnp.float32))


def _time_call(fn, *args, iters=4, warmup=2):
    for _ in range(warmup):
        out = fn(*args)
    _sync(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    _sync(out)
    return (time.perf_counter() - t0) / iters


def _slope(make_fn, args, r1=8, r2=48):
    f1 = make_fn(r1)
    f2 = make_fn(r2)
    t1 = _time_call(f1, *args)
    t2 = _time_call(f2, *args)
    return max((t2 - t1) / (r2 - r1), 1e-9)


def sweep(batch=8, heads=12, seq=1024, d=64, causal=True):
    rng = np.random.default_rng(0)
    shp = (batch, seq, heads, d)   # paddle layout for our kernel
    q = jnp.asarray(rng.normal(size=shp) * 0.1, jnp.bfloat16)
    k = jnp.asarray(rng.normal(size=shp) * 0.1, jnp.bfloat16)
    v = jnp.asarray(rng.normal(size=shp) * 0.1, jnp.bfloat16)
    flops_f = 4.0 * batch * heads * seq * seq * d * (0.5 if causal else 1.0)
    results = {}

    def measure(name, one_fwd):
        def mk_f(reps):
            @jax.jit
            def f(q, k, v):
                def body(c, i):
                    return c + one_fwd(q + i.astype(q.dtype) * 1e-6,
                                       k, v), None
                return jax.lax.scan(body, jnp.zeros_like(q),
                                    jnp.arange(reps))[0]
            return f

        grad = jax.grad(
            lambda q, k, v: one_fwd(q, k, v).astype(jnp.float32).sum(),
            argnums=(0, 1, 2))

        def mk_b(reps):
            @jax.jit
            def f(q, k, v):
                def body(c, i):
                    dq, _, _ = grad(q + i.astype(q.dtype) * 1e-6, k, v)
                    return c + dq.astype(q.dtype), None
                return jax.lax.scan(body, jnp.zeros_like(q),
                                    jnp.arange(reps))[0]
            return f

        try:
            tf_ = _slope(mk_f, (q, k, v))
            tb = _slope(mk_b, (q, k, v))
        except Exception as e:
            _log(f"{name}: FAILED {type(e).__name__}: {e}")
            return
        # the grad call runs fwd (residuals) + bwd kernels, which is
        # exactly one training step's attention work — so gradcall_ms IS
        # the per-step cost; fwd_ms alone is the inference cost
        results[name] = {
            "fwd_ms": round(tf_ * 1e3, 3),
            "gradcall_ms": round(tb * 1e3, 3),
            "fwd_tflops": round(flops_f / tf_ / 1e12, 2),
            "train_step_ms": round(tb * 1e3, 3)}
        _log(f"{name}: fwd {tf_*1e3:.3f} ms ({flops_f/tf_/1e12:.1f} TF/s) "
             f"gradcall {tb*1e3:.3f} ms")

    # ours, block-size grid
    from paddle_tpu.ops.pallas import flash_attention as fa
    for bq, bk in ((128, 128), (256, 256), (256, 512), (512, 256),
                   (512, 512), (512, 1024), (1024, 512), (1024, 1024)):
        if bq > seq or bk > seq:
            continue
        measure(f"ours_{bq}x{bk}", functools.partial(
            lambda q, k, v, blocks: fa.flash_attention(
                q, k, v, causal=causal, blocks=blocks), blocks=(bq, bk)))

    # jax in-tree pallas flash attention (layout [B,H,S,D])
    try:
        from jax.experimental.pallas.ops.tpu import flash_attention as jfa

        def intree(q, k, v):
            qt = jnp.swapaxes(q, 1, 2)
            kt = jnp.swapaxes(k, 1, 2)
            vt = jnp.swapaxes(v, 1, 2)
            o = jfa.flash_attention(qt, kt, vt, causal=causal,
                                    sm_scale=1.0 / np.sqrt(d))
            return jnp.swapaxes(o, 1, 2)
        measure("jax_intree", intree)
    except Exception as e:
        _log(f"jax_intree unavailable: {e}")

    # naive XLA
    def xla(q, k, v):
        qt = jnp.swapaxes(q, 1, 2).astype(jnp.float32)
        kt = jnp.swapaxes(k, 1, 2).astype(jnp.float32)
        vt = jnp.swapaxes(v, 1, 2)
        s = jnp.einsum("bhqd,bhkd->bhqk", qt, kt,
                       preferred_element_type=jnp.float32) / np.sqrt(d)
        if causal:
            i = jnp.arange(seq)
            s = jnp.where((i[:, None] >= i[None, :])[None, None], s, -1e30)
        p = jax.nn.softmax(s, -1).astype(jnp.bfloat16)
        o = jnp.einsum("bhqk,bhkd->bhqd", p, vt)
        return jnp.swapaxes(o, 1, 2)
    measure("xla_naive", xla)

    return results


if __name__ == "__main__":
    res = sweep()
    print(json.dumps(res, indent=2))
    if len(sys.argv) > 1:
        with open(sys.argv[1], "w") as f:
            json.dump(res, f, indent=2)

"""Repo-persisted measurement cache for bench.py's expensive evidence.

The driver runs bench.py under a hard time budget; round 3 blew it
(BENCH_r03.json rc:124) re-measuring ~20 minutes of calibration and
secondary-model compiles that had not changed since the previous run.
Everything expensive is therefore persisted HERE, keyed by

    (device kind, entry name)  ->  {"code_version": ..., "value": ...}

with ``code_version`` a content hash of the source files the measurement
depends on — a stale hash forces a re-measure, so numbers can never
outlive the code that produced them. The cache lives inside the repo
(``benchmarks/measured/``) and is committed: the per-round environment
wipes ``~/.cache``, and a cache that does not survive the round boundary
saves nothing.
"""
from __future__ import annotations

import hashlib
import json
import os
import re

_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "measured")


def _path(device_kind: str) -> str:
    slug = re.sub(r"[^A-Za-z0-9._-]+", "_", str(device_kind)).strip("_")
    return os.path.join(_DIR, f"{slug or 'unknown'}.json")


def code_version(*files: str) -> str:
    """Content hash over the given source files (repo-relative)."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    h = hashlib.sha256()
    for f in sorted(files):
        p = os.path.join(root, f)
        try:
            with open(p, "rb") as fh:
                h.update(fh.read())
        except OSError:
            h.update(b"missing:" + f.encode())
    return h.hexdigest()[:16]


def load(device_kind: str, name: str, version: str):
    """The cached value for (device, name) if its code_version matches,
    else None."""
    try:
        with open(_path(device_kind)) as f:
            data = json.load(f)
    except Exception:
        return None
    ent = data.get(name)
    if not isinstance(ent, dict) or ent.get("code_version") != version:
        return None
    return ent.get("value")


def store(device_kind: str, name: str, version: str, value) -> None:
    path = _path(device_kind)
    try:
        with open(path) as f:
            data = json.load(f)
    except Exception:
        data = {}
    data[name] = {"code_version": version, "value": value}
    os.makedirs(_DIR, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(data, f, indent=1, sort_keys=True)
    os.replace(tmp, path)

#!/usr/bin/env python
"""Profile the bench train step on the real chip and print the op-level
time breakdown (xprof framework_op_stats). argv[1] = optional trace dir."""
from __future__ import annotations

import glob
import os
import sys

import numpy as np


def main():
    import jax

    import paddle_tpu as paddle
    import paddle_tpu.amp as amp
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    trace_dir = sys.argv[1] if len(sys.argv) > 1 else "/tmp/pdtpu_trace"

    cfg = GPTConfig(vocab_size=50304, hidden_size=768, num_layers=12,
                    num_heads=12, max_seq_len=1024, dropout=0.0,
                    recompute=True, recompute_policy="dots_saveable")
    batch, seq = 8, 1024
    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    model.train()
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())
    model, opt = amp.decorate(models=model, optimizers=opt, level="O2",
                              dtype="bfloat16", master_weight=True)

    @paddle.jit.to_static
    def train_step(ids, labels):
        with amp.auto_cast(level="O2", dtype="bfloat16"):
            loss = model(ids, labels)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    rng = np.random.default_rng(0)

    def batch_fn():
        ids = rng.integers(0, cfg.vocab_size, (batch, seq)).astype(np.int32)
        lab = rng.integers(0, cfg.vocab_size, (batch, seq)).astype(np.int32)
        return paddle.to_tensor(ids), paddle.to_tensor(lab)

    for _ in range(3):
        loss = train_step(*batch_fn())
    float(loss)

    with jax.profiler.trace(trace_dir):
        for _ in range(5):
            loss = train_step(*batch_fn())
        float(loss)

    # ---- parse with xprof
    from xprof.convert import raw_to_tool_data as rtd

    run_dirs = sorted(glob.glob(os.path.join(trace_dir, "plugins",
                                             "profile", "*")))
    data, _ = rtd.xspace_to_tool_data([run_dirs[-1]],
                                      "framework_op_stats", {})
    import csv
    import io
    if isinstance(data, bytes):
        data = data.decode()
    rows = list(csv.DictReader(io.StringIO(data)))
    agg = {}
    for r in rows:
        if r.get("host_or_device") != "Device":
            continue
        cat = r.get("category") or r.get("type", "?")
        name = r.get("operation") or r.get("op_name", "?")
        t = float(r.get("total_self_time_in_us") or
                  r.get("self_time_us") or 0)
        occ = int(float(r.get("occurrences") or 1))
        k = (cat, name[:60])
        a = agg.setdefault(k, [0.0, 0])
        a[0] += t
        a[1] += occ
    total = sum(a[0] for a in agg.values())
    print(f"\ndevice total self time: {total/1e3:.2f} ms over 5 steps "
          f"(= {total/5e3:.2f} ms/step)\n")
    print(f"{'category':24s} {'op':60s} {'ms/step':>9s} {'%':>6s} {'n':>6s}")
    for (cat, name), (t, occ) in sorted(agg.items(),
                                        key=lambda kv: -kv[1][0])[:40]:
        print(f"{cat:24s} {name:60s} {t/5e3:9.3f} {100*t/total:6.2f} "
              f"{occ:6d}")
    # category rollup
    cats = {}
    for (cat, _), (t, _o) in agg.items():
        cats[cat] = cats.get(cat, 0.0) + t
    print("\n-- by category --")
    for cat, t in sorted(cats.items(), key=lambda kv: -kv[1]):
        print(f"{cat:40s} {t/5e3:9.3f} ms/step {100*t/total:6.2f}%")


if __name__ == "__main__":
    main()

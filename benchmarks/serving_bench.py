#!/usr/bin/env python
"""Serving measurements with roofline accounting (ISSUE 3; VERDICT r5
weak 4: "serving rows are tunnel-launch-bound and have no roofline
accounting").  Reference bar: the fused serving kernels
``paddle/phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu``
and ``masked_multihead_attention_kernel.cu`` (SURVEY C12/C13).

Row schema (CHANGED in round 6 — consumers of the ``serving`` cache
entry note):

    batch, prompt_len, new_tokens, kv_cache, decode_window  — config
    ms_per_token       — wall per decode step (per-request latency)
    tokens_per_sec     — batch * new_tokens / wall
    wall_s             — best-of-3 wall time
    roofline_ms        — HBM-roofline target for one decode step:
                         (weight bytes + KV bytes read) / device HBM
                         bandwidth.  Decode is bandwidth-bound, so this
                         is the "as fast as the hardware allows" floor.
    roofline_x         — ms_per_token / roofline_ms (1.0 = at roofline)
    launch_ms          — measured per-dispatch round-trip cost times
                         dispatches-per-token (prefill + one scalar
                         step + ceil(new/K) windows, amortized)
    launch_share       — launch_ms / ms_per_token: how much of the row
                         is fixed dispatch overhead rather than device
                         work (VERDICT r5: ~4.4 of 9.05 ms at K=16)

plus a ``continuous_mixed`` row: a mixed-arrival workload (staggered
prompt/output lengths) through ``inference.ContinuousBatchingEngine``
— admissions ragged-batched with ongoing decodes, retirements
returning pages to the free list.  Its ``tokens_per_sec`` is the
continuous-batching throughput claim and must beat the fixed-batch
``paged_b8`` row to justify the scheduler.

plus an ``overload`` row (ISSUE 5): the same engine driven PAST its
capacity — page pool sized below the arrival working set, a bounded
admission queue, and tight deadlines on a slice of the requests — so
the overload policies (preempt-and-requeue, reject, timeout) are what
is being measured.  Reports ``goodput_tokens_per_sec`` (tokens of
normally-finished requests only), ``preemptions``, ``timeouts``,
``rejected`` and ``completed_ok``; a lab engine crashes on this
workload, a serving engine degrades and the row quantifies the
degradation.

plus two QUANT rows (ISSUE 7) whose roofline is recomputed from the
QUANTIZED bytes — the whole point of the int8 paths is to lower the
bandwidth floor itself, so the target column must move with them:

* ``quant_b8`` — the fixed-batch engine workload twice over identical
  traffic, ``kv_quant`` off then on (int8 KV pages + in-kernel
  dequant): per-token latency both ways, ``roofline_ms`` from int8+
  scale KV bytes, ``kv_page_bytes`` on/off (the halved-bytes claim),
  ``pages_per_request``, and the ``roofline_x`` delta vs the fp twin.
* ``weight_only_b1`` — ``generate(kv_cache='paged')`` on a
  ``weight_only_quantize``d model (int8 weights through the Pallas
  fused dequant-matmul) vs the same fp model: ms/token both ways,
  ``roofline_ms`` from int8 weight bytes + per-channel scales, and the
  weight-byte ratio.

plus a ``shared_prefix`` row (ISSUE 6): a system-prompt-heavy workload
(~90% of arrivals share a long prefix) through the engine with the
cross-request KV prefix cache (``inference/prefix_cache.py``) on vs.
off.  Reports the ROADMAP measure directly:
``prefill_tokens_computed`` vs. ``prefill_tokens_requested`` (the
saved fraction is the cache's compute win), mean time-to-first-token
with and without the cache, plus ``cache_hits``/``cache_hit_tokens``/
``evictions``.  The CPU tiny-model smoke
(``tests/test_serving_engine.py``) validates the accounting; absolute
times are TPU-measured.

plus a ``speculative`` row (ISSUE 9): a repetitive-text workload
(prompts tile a short motif, the regime where the model-free n-gram /
prompt-lookup proposer finds its continuations in context) driven
twice over identical traffic — ``spec_decode`` off (plain decode) then
on.  Reports ``accepted_tokens_per_step`` (the verify multiplier: mean
tokens emitted per slot per verify dispatch, from the engine's
``spec_accepted_per_step`` histogram), ``spec_accept_rate``,
tokens/sec both ways, and the ``outputs_equal`` gate — greedy
speculative output must be BITWISE the plain stream, so speculation
can only ever move throughput, never tokens.  The n-gram proposer runs
on the CPU smoke (``tests/test_speculative.py``); absolute times are
TPU claims.

plus ``tp2``/``tp4`` rows (ISSUE 13): the fixed-batch engine workload
single-device vs TP-sharded over a 2/4-device mesh axis
(``ContinuousBatchingEngine(mesh=)`` — weights column/row split per
the canonical Megatron rules, KV pools sharded by kv-head, one psum
at the attention output and MLP reduce).  The TP roofline is the
PER-DEVICE floor (``roofline_ms / tp``: each shard reads 1/tp of the
weight and KV bytes) and ``outputs_equal`` gates token-identical
greedy streams.

plus a ``disagg`` row (ISSUE 13): a latency class (long decodes)
alone and under a concurrent prefill storm, colocated vs
``inference.DisaggServer`` (prefill and decode worker groups with
the KV-page handoff).  Reports decode ``tpot_p99_ms`` for all four
cells — the claim is that the disagg decode group's p99 stays flat
under the storm while the colocated engine's tracks it — plus
``handoff_ms_avg``, ``transfer_bytes``, ``handoffs`` from the
coordinator's registry.

plus a ``metrics_overhead`` micro-row (ISSUE 8): identical engine
traffic with ``PDTPU_METRICS`` on vs off, reporting the tokens/sec
delta — the always-on observability runtime's <= 3% cost claim.  The
``continuous_mixed``/``overload``/``shared_prefix`` rows' TTFT/TPOT/
queue-time columns are derived from the engine's OWN event timelines
(``engine.metrics()``, ``paddle_tpu/observability/serving.py``)
instead of ad-hoc host timers: prefill chunks and decodes share one
ragged dispatch, so phase attribution must come from engine events.
Since ISSUE 14 the on half also arms the SLO guardrails + stall
watchdog (``slo=``/``watchdog_ms=``), so the overhead claim covers
judgment-layer cost too, and the ``continuous_mixed``/``overload``/
``disagg`` rows carry ``slo_ok``/``budget_burn`` columns — the SLO
engine's verdict (all objectives met; worst slow-window burn rate)
on the traffic the row measured, from the SAME percentile math the
report columns use (``observability.metrics.percentile_from_counts``).

Results persist via benchmarks/measured_cache.py and surface as a
compact ``serving`` entry in bench.py's enriched record and in
BASELINE.md.  Run standalone on the real chip:

    PYTHONPATH=/root/repo:/root/.axon_site python benchmarks/serving_bench.py
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
os.environ.setdefault(
    "PDTPU_CACHE_DIR", os.path.join(_REPO, "benchmarks", "measured"))

# HBM bandwidth by device kind, GB/s (vendor specs; used for the
# roofline TARGET column, not for any measured number)
_HBM_GBPS = {
    "TPU v4": 1228.0,
    "TPU v5 lite": 819.0,
    "TPU v5e": 819.0,
    "TPU v5p": 2765.0,
    "TPU v6 lite": 1640.0,
    "TPU v6e": 1640.0,
}


def _hbm_gbps(dev) -> float:
    kind = str(getattr(dev, "device_kind", ""))
    for k, v in _HBM_GBPS.items():
        if k.lower() in kind.lower():
            return v
    return 819.0  # assume v5e-class when unknown


def _build_model():
    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    cfg = GPTConfig(vocab_size=50304, hidden_size=768, num_layers=12,
                    num_heads=12, max_seq_len=2048, dropout=0.0)
    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    model.eval()
    return cfg, model


def _param_bytes(model) -> int:
    total = 0
    for p in model.parameters():
        n = 1
        for s in p.shape:
            n *= int(s)
        total += n * int(np.dtype(str(p.dtype).split(".")[-1]).itemsize)
    return total


def _kv_bytes_per_seq(cfg, avg_len, itemsize=4, scale_bytes=0) -> int:
    """KV bytes one sequence's cache reads per step; ``itemsize`` 1 +
    ``scale_bytes`` 4 is the int8 page-pool layout (one f32 absmax
    scale per head per token slot riding the side-pools)."""
    n_kv = getattr(cfg, "num_kv_heads", cfg.num_heads)
    return 2 * cfg.num_layers * n_kv * avg_len \
        * (cfg.head_dim * itemsize + scale_bytes)


def _quant_param_bytes(model) -> int:
    """Weight bytes of a ``weight_only_quantize``d model: Linear
    weights at 1 byte + a 4-byte per-out-channel scale; everything
    else (embeddings, norms, biases) at float width."""
    from paddle_tpu.nn.layers import Linear
    total = _param_bytes(model)
    for layer in model.sublayers(include_self=True):
        if isinstance(layer, Linear):
            n_in, n_out = (int(s) for s in layer.weight.shape)
            total -= n_in * n_out * 4           # fp32 weight out...
            total += n_in * n_out + n_out * 4   # ...int8 + scales in
    return total


def roofline_ms(cfg, model, batch, prompt_len, new_tokens, gbps,
                kv_itemsize=4, kv_scale_bytes=0,
                param_bytes=None) -> float:
    """HBM floor for ONE decode step serving ``batch`` sequences: every
    weight byte read once, plus each sequence's (average-length) KV.
    The quant rows move the floor itself: ``kv_itemsize=1,
    kv_scale_bytes=4`` prices int8 KV pages, ``param_bytes`` overrides
    the weight term for int8 weights."""
    avg_len = prompt_len + new_tokens // 2
    bytes_step = (param_bytes if param_bytes is not None
                  else _param_bytes(model)) \
        + batch * _kv_bytes_per_seq(cfg, avg_len, kv_itemsize,
                                    kv_scale_bytes)
    return bytes_step / (gbps * 1e9) * 1e3


def _tl_node(eng, name) -> dict:
    node = eng.metrics()
    for part in ("serving." + name).split("."):
        node = node.get(part, {})
    return node


def _tl_pct(eng, name, q=0.99) -> float:
    """Percentile of one serving-timeline histogram — the SHARED
    ``observability.metrics.percentile_from_counts`` implementation
    (ISSUE 14: one home for the math, so the SLO engine's runtime
    judgment and this report column can never disagree on what a p99
    is).  The ``disagg`` row's decode-p99 claim reads this."""
    from paddle_tpu.observability.metrics import percentile_from_counts
    node = _tl_node(eng, name)
    return percentile_from_counts(node.get("buckets", []),
                                  node.get("counts", []),
                                  node.get("count", 0), q)


def _tl_mean(eng, name) -> float:
    """Mean of one serving-timeline histogram from ``engine.metrics()``
    (ISSUE 8): TTFT/TPOT columns come from the engine's OWN event
    timelines — the ragged mixed program batches prefill chunks and
    decodes of many requests into one dispatch, so host-side timer
    wrapping cannot attribute phases; the engine's scheduling events
    can.  Reads the snapshot's own ``mean`` (computed sum/count inside
    the histogram's locked ``_snap`` — the one implementation)."""
    return _tl_node(eng, name).get("mean", 0.0)


# default SLO objectives armed on the engine-driven rows (ISSUE 14):
# generous CPU-smoke-safe thresholds — the slo_ok/budget_burn columns
# REPORT the judgment layer's verdict on the measured traffic, they do
# not gate the bench.  The metrics_overhead row arms the same spec plus
# the stall watchdog, so its <= 3% claim covers guardrails-on serving.
_SLO_SPEC = ("ttft_p95_ms=2000,tpot_p99_ms=500,queue_p95_ms=5000,"
             "goodput=0.9")
_WATCHDOG_MS = 30000.0


def _slo_cols(eng) -> dict:
    """``slo_ok`` / ``budget_burn`` columns from an engine's armed SLO
    specs (all-ok verdict and the worst slow-window burn rate)."""
    sts = eng.slo_status()
    return {
        "slo_ok": bool(all(s["ok"] for s in sts)) if sts else True,
        "budget_burn": round(max((s["burn_slow"] for s in sts),
                                 default=0.0), 4),
    }


def measure_launch_ms() -> float:
    """Per-dispatch round-trip cost of this host<->device link: one
    trivial jitted program, timed submit-to-readback (the fixed cost
    every window/prefill dispatch pays regardless of device work)."""
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda x: x + 1)
    x = jnp.zeros((8,), jnp.float32)
    np.asarray(f(x))  # compile
    best = float("inf")
    for _ in range(20):
        t0 = time.perf_counter()
        np.asarray(f(x))
        best = min(best, time.perf_counter() - t0)
    return best * 1e3


def measure():
    import paddle_tpu as paddle
    from paddle_tpu.models.generation import generate

    import jax

    cfg, model = _build_model()
    dev = jax.devices()[0]
    gbps = _hbm_gbps(dev)
    launch = measure_launch_ms()
    rng = np.random.default_rng(0)
    rows = {}

    # whole-program audit bookkeeping (ISSUE 16): count findings only
    # from the serving programs this bench compiles
    from paddle_tpu import analysis as _analysis
    _analysis.audit_counts(reset=True)

    def finish(name, row, batch, prompt_len, new_tokens, window,
               n_dispatch):
        rl = roofline_ms(cfg, model, batch, prompt_len, new_tokens, gbps)
        lm = launch * n_dispatch / new_tokens
        row["roofline_ms"] = round(rl, 3)
        row["roofline_x"] = round(row["ms_per_token"] / rl, 1)
        row["launch_ms"] = round(lm, 3)
        row["launch_share"] = round(lm / row["ms_per_token"], 3)
        # host dispatches amortized per generated token (ISSUE 18:
        # the decode megakernel's target metric — fewer fused kernels
        # per compiled step shrink launch_share, this column tracks
        # the program-boundary count the windows amortize)
        row["dispatches_per_token"] = round(n_dispatch / new_tokens, 3)
        rows[name] = row
        print(f"{name}: {row['ms_per_token']} ms/token "
              f"({row['tokens_per_sec']} tok/s, roofline x"
              f"{row['roofline_x']}, launch {row['launch_share']:.0%})",
              file=sys.stderr, flush=True)

    def run(name, batch, prompt_len, new_tokens, kv, window):
        ids = paddle.to_tensor(
            rng.integers(0, cfg.vocab_size,
                         (batch, prompt_len)).astype(np.int32))
        kw = dict(max_new_tokens=new_tokens, temperature=0.0,
                  kv_cache=kv, decode_window=window)
        out = generate(model, ids, **kw)       # compile + warm
        np.asarray(out._read())
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            out = generate(model, ids, **kw)
            np.asarray(out._read())            # full sync readback
            best = min(best, time.perf_counter() - t0)
        ms_tok = best * 1e3 / new_tokens
        row = {
            "batch": batch, "prompt_len": prompt_len,
            "new_tokens": new_tokens, "kv_cache": kv,
            "decode_window": window,
            "ms_per_token": round(ms_tok, 2),
            "tokens_per_sec": round(batch * new_tokens / best, 1),
            "wall_s": round(best, 3),
        }
        # dispatches: prefill + first scalar step + scanned windows
        n_disp = 2 + -(-new_tokens // window)
        finish(name, row, batch, prompt_len, new_tokens, window, n_disp)

    # single-request latency rows: 128-token prompt, 64 new tokens
    run("dense_b1", 1, 128, 64, "dense", 16)
    run("paged_b1", 1, 128, 64, "paged", 16)
    # multi-request batched decode over the page pools: 8 concurrent
    # sequences through one compiled windowed-decode program (the
    # fixed-batch bar continuous_mixed has to beat)
    run("paged_b8", 8, 128, 64, "paged", 16)
    # long-context serving check: 1024-token prompt, paged
    run("paged_b1_long", 1, 1024, 64, "paged", 16)
    rows["continuous_mixed"] = _measure_continuous(
        cfg, model, gbps, launch)
    rows["overload"] = _measure_overload(cfg, model)
    rows["shared_prefix"] = _measure_shared_prefix(cfg, model)
    rows["quant_b8"] = _measure_quant(cfg, model, gbps)
    rows["weight_only_b1"] = _measure_weight_only(cfg, model, gbps)
    rows["speculative"] = _measure_speculative(cfg, model)
    rows["metrics_overhead"] = _measure_metrics_overhead(cfg, model)
    rows["tp2"] = _measure_tp(cfg, model, gbps, 2)
    rows["tp4"] = _measure_tp(cfg, model, gbps, 4)
    rows["disagg"] = _measure_disagg(cfg, model)
    rows["fleet"] = _measure_fleet(cfg, model)
    # migration columns (ISSUE 20) ride the fleet row: drain latency
    # both ways, warm pages shipped, and the bitwise gate
    mig = _measure_migration(cfg, model)
    rows["fleet"].update({
        "drain_ms_migrate": mig["drain_ms_migrate"],
        "drain_ms_wait": mig["drain_ms_wait"],
        "migrated_pages": mig["migrated_pages"],
        "prefill_tokens_saved": mig["prefill_tokens_saved"],
        "outputs_equal_migration": mig["outputs_equal"]
        and mig["pages_leaked"] == 0})
    # per-code finding counts from every serving program compiled above
    # (engine caches, decode windows, TP wrappers); the regression
    # sentinel judges PDT* leaves lower-is-better
    rows["analysis"] = {"findings": _analysis.audit_counts()}
    # decode megakernel calibration (ISSUE 18): exact per-layer
    # dispatch counts, unfused vs fused — a count, not a timing, so it
    # rides every serving measurement regardless of device
    import calibrate as _calibrate
    rows["_calibration"] = {
        "decode_dispatches": _calibrate.measure_decode_dispatches()}
    return rows


def _mixed_workload(rng, n_requests, prompt_range, new_range):
    """Staggered arrivals with ragged prompt/output lengths — the mix a
    static batch cannot serve without padding every request to the
    longest."""
    return [(int(rng.integers(*prompt_range)),
             int(rng.integers(*new_range)))
            for _ in range(n_requests)]


def _measure_continuous(cfg, model, gbps, launch, slots=8,
                        max_seq_len=512, prompt_range=(32, 257),
                        new_range=(16, 65), n_requests=16,
                        page_size=16, decode_window=16,
                        prefill_chunk=128):
    from paddle_tpu.inference import ContinuousBatchingEngine

    rng = np.random.default_rng(1)
    specs = _mixed_workload(rng, n_requests, prompt_range, new_range)

    def drive():
        eng = ContinuousBatchingEngine(
            model, max_slots=slots, page_size=page_size,
            max_seq_len=max_seq_len, decode_window=decode_window,
            prefill_chunk=prefill_chunk, slo=_SLO_SPEC)
        # staggered arrivals: half queued up front, the rest trickling
        # in while earlier requests decode (admissions mid-stream)
        pending = list(specs)
        for p_len, n_new in pending[:len(pending) // 2]:
            eng.add_request(
                rng.integers(0, cfg.vocab_size, p_len).astype(np.int32),
                n_new)
        pending = pending[len(pending) // 2:]
        t0 = time.perf_counter()
        while eng.has_work or pending:
            if pending and eng.stats["steps"] % 2 == 0:
                p_len, n_new = pending.pop(0)
                eng.add_request(
                    rng.integers(0, cfg.vocab_size,
                                 p_len).astype(np.int32), n_new)
            eng.step()
        wall = time.perf_counter() - t0
        return eng, wall

    eng, _ = drive()                 # compile + warm (both programs)
    eng, wall = drive()
    toks = eng.stats["tokens_generated"]
    ms_tok = wall * 1e3 / max(toks / slots, 1)   # per-slot latency-ish
    avg_prompt = int(np.mean([s[0] for s in specs]))
    avg_new = int(np.mean([s[1] for s in specs]))
    rl = roofline_ms(cfg, model, slots, avg_prompt, avg_new, gbps)
    n_disp = eng.stats["decode_dispatches"]
    lm = launch * n_disp / max(toks / slots, 1)
    row = {
        "batch": slots, "prompt_len": avg_prompt, "new_tokens": avg_new,
        "kv_cache": "paged", "decode_window": decode_window,
        "requests": len(specs),
        "ms_per_token": round(ms_tok, 2),
        "tokens_per_sec": round(toks / wall, 1),
        "wall_s": round(wall, 3),
        "roofline_ms": round(rl, 3),
        "roofline_x": round(ms_tok / rl, 1),
        "launch_ms": round(lm, 3),
        "launch_share": round(min(lm / ms_tok, 1.0), 3),
        "dispatches_per_token": round(n_disp / max(toks, 1), 3),
        "pages_allocated": eng.stats["pages_allocated"],
        "peak_pages_in_use": eng.stats["peak_pages_in_use"],
        # per-request latency columns from the engine timelines
        "ttft_ms_avg": round(_tl_mean(eng, "ttft_ms"), 2),
        "tpot_ms_avg": round(_tl_mean(eng, "tpot_ms"), 2),
        "queue_ms_avg": round(_tl_mean(eng, "queue_ms"), 2),
        # SLO judgment on the measured traffic (ISSUE 14)
        **_slo_cols(eng),
    }
    print(f"continuous_mixed: {row['tokens_per_sec']} tok/s over "
          f"{row['requests']} staggered requests (TTFT "
          f"{row['ttft_ms_avg']} ms, TPOT {row['tpot_ms_avg']} ms)",
          file=sys.stderr, flush=True)
    return row


def _measure_overload(cfg, model, slots=8, max_seq_len=512,
                      prompt_range=(32, 257), new_range=(16, 65),
                      n_requests=24, page_size=16, decode_window=16,
                      prefill_chunk=128, max_queue=8,
                      deadline_every=6, deadline_ms=300.0):
    """Drive the engine PAST capacity and measure the degradation the
    overload policies buy: the page pool holds ~55% of the slots'
    worst-case working set (growth preempts), the queue is bounded
    with policy 'reject' (arrivals past depth shed), and every
    ``deadline_every``-th request carries a tight deadline."""
    from paddle_tpu.inference import ContinuousBatchingEngine

    rng = np.random.default_rng(2)
    specs = _mixed_workload(rng, n_requests, prompt_range, new_range)
    np_per_seq = -(-max_seq_len // page_size)
    total_pages = 1 + int(slots * np_per_seq * 0.55)

    def drive():
        from paddle_tpu.core.errors import QueueFullError

        eng = ContinuousBatchingEngine(
            model, max_slots=slots, page_size=page_size,
            max_seq_len=max_seq_len, total_pages=total_pages,
            decode_window=decode_window, prefill_chunk=prefill_chunk,
            max_queue=max_queue, queue_policy="reject", slo=_SLO_SPEC)
        pending = list(enumerate(specs))
        done = {}
        rejected = 0
        t0 = time.perf_counter()
        while eng.has_work or pending:
            # arrivals outpace service: two per engine step
            for _ in range(2):
                if not pending:
                    break
                i, (p_len, n_new) = pending.pop(0)
                dl = (deadline_ms if i % deadline_every == 0
                      else None)
                try:
                    eng.add_request(
                        rng.integers(0, cfg.vocab_size,
                                     p_len).astype(np.int32),
                        n_new, deadline_ms=dl)
                except QueueFullError:  # load shed by design; anything
                    rejected += 1       # else must FAIL the bench
            for c in eng.step():
                done[c.request_id] = c
        wall = time.perf_counter() - t0
        return eng, done, rejected, wall

    drive()                            # compile + warm both programs
    eng, done, rejected, wall = drive()
    ok = [c for c in done.values() if c.ok]
    good_toks = sum(c.tokens.size for c in ok)
    st = eng.stats
    row = {
        "batch": slots, "kv_cache": "paged",
        "decode_window": decode_window,
        "requests": len(specs), "total_pages": total_pages,
        "max_queue": max_queue,
        "wall_s": round(wall, 3),
        "tokens_per_sec": round(st["tokens_generated"] / wall, 1),
        "goodput_tokens_per_sec": round(good_toks / wall, 1),
        "completed_ok": len(ok),
        "preemptions": st["preemptions"],
        "timeouts": st["timeouts"],
        "rejected": rejected,
        "pages_leaked": st["pages_in_use"],   # must be 0
        # overload latency columns (engine timelines): queue time is
        # the column overload moves first, TTFT/TPOT show what the
        # admitted slice still got
        "ttft_ms_avg": round(_tl_mean(eng, "ttft_ms"), 2),
        "tpot_ms_avg": round(_tl_mean(eng, "tpot_ms"), 2),
        "queue_ms_avg": round(_tl_mean(eng, "queue_ms"), 2),
        # the overload row is exactly where the SLO layer earns its
        # keep: goodput burns budget as requests time out / shed
        **_slo_cols(eng),
    }
    print(f"overload: {row['goodput_tokens_per_sec']} good tok/s "
          f"({row['completed_ok']}/{row['requests']} ok, "
          f"{row['preemptions']} preempts, {row['timeouts']} timeouts, "
          f"{row['rejected']} rejected)", file=sys.stderr, flush=True)
    return row


def _measure_shared_prefix(cfg, model, slots=8, max_seq_len=512,
                           shared_len=192, tail_range=(8, 49),
                           new_tokens=32, n_requests=20,
                           hit_every=10, page_size=16,
                           decode_window=16, prefill_chunk=128,
                           seed=3, warm=True):
    """System-prompt-heavy traffic (ISSUE 6): every request but each
    ``hit_every``-th shares a ``shared_len``-token prefix (~90% prefix
    hit rate), driven twice — prefix cache OFF then ON — over identical
    arrivals.  The ROADMAP measure: prefill tokens computed vs.
    requested and mean TTFT at a high hit rate.  Works on the CPU tiny
    model too (the accounting smoke in tests/test_serving_engine.py
    uses it); absolute times only mean something on the TPU."""
    from paddle_tpu.inference import ContinuousBatchingEngine

    rng = np.random.default_rng(seed)
    shared = rng.integers(0, cfg.vocab_size, shared_len).astype(np.int32)
    specs = []
    for i in range(n_requests):
        tail = rng.integers(0, cfg.vocab_size,
                            int(rng.integers(*tail_range))).astype(
                                np.int32)
        if i % hit_every == hit_every - 1:    # ~10% cold prompts
            prompt = rng.integers(
                0, cfg.vocab_size,
                shared_len + tail.size).astype(np.int32)
        else:
            prompt = np.concatenate([shared, tail])
        specs.append(prompt)

    def drive(prefix_cache):
        # TTFT comes from the engine's own timelines (ISSUE 8) — the
        # old host-side slot scan measured step-granular arrival of
        # out_toks, not the enqueue->first-token window the engine's
        # events pin exactly
        eng = ContinuousBatchingEngine(
            model, max_slots=slots, page_size=page_size,
            max_seq_len=max_seq_len, decode_window=decode_window,
            prefill_chunk=prefill_chunk, prefix_cache=prefix_cache)
        pending = list(enumerate(specs))
        t0 = time.perf_counter()
        while eng.has_work or pending:
            for _ in range(2):                # staggered arrivals
                if not pending:
                    break
                _i, prompt = pending.pop(0)
                eng.add_request(prompt, new_tokens)
            eng.step()
        wall = time.perf_counter() - t0
        return eng, wall

    if warm:                                  # compile + warm (the CPU
        drive(False)                          # smoke skips the timing
    eng_off, wall_off = drive(False)          # rigor for speed)
    eng_on, wall_on = drive(True)
    st_on, st_off = eng_on.stats, eng_off.stats
    row = {
        "batch": slots, "kv_cache": "paged", "requests": n_requests,
        "shared_len": shared_len, "new_tokens": new_tokens,
        "hit_rate_cfg": round(1.0 - 1.0 / hit_every, 2),
        "prefill_tokens_requested": st_on["prefill_tokens_requested"],
        "prefill_tokens_computed": st_on["prefill_tokens_computed"],
        "prefill_saved_frac": round(
            1.0 - st_on["prefill_tokens_computed"]
            / max(st_on["prefill_tokens_requested"], 1), 3),
        "cache_hits": st_on["cache_hits"],
        "cache_hit_tokens": st_on["cache_hit_tokens"],
        "evictions": st_on["evictions"],
        "cached_pages": st_on["cached_pages"],
        "ttft_ms_avg": round(_tl_mean(eng_on, "ttft_ms"), 2),
        "ttft_ms_avg_nocache": round(_tl_mean(eng_off, "ttft_ms"), 2),
        "tpot_ms_avg": round(_tl_mean(eng_on, "tpot_ms"), 2),
        "tpot_ms_avg_nocache": round(_tl_mean(eng_off, "tpot_ms"), 2),
        "tokens_per_sec": round(
            st_on["tokens_generated"] / wall_on, 1),
        "tokens_per_sec_nocache": round(
            st_off["tokens_generated"] / wall_off, 1),
        "wall_s": round(wall_on, 3),
        "pages_leaked": st_on["pages_in_use"],   # must be 0
    }
    print(f"shared_prefix: {row['prefill_saved_frac']:.0%} prefill "
          f"saved ({row['prefill_tokens_computed']}/"
          f"{row['prefill_tokens_requested']} tokens computed), TTFT "
          f"{row['ttft_ms_avg']} ms vs {row['ttft_ms_avg_nocache']} ms "
          f"uncached", file=sys.stderr, flush=True)
    return row


def _measure_quant(cfg, model, gbps, slots=8, prompt_len=128,
                   new_tokens=64, page_size=16, decode_window=16,
                   prefill_chunk=128, max_seq_len=512, q_block=8,
                   seed=4, warm=True):
    """ISSUE 7 ``quant_b8``: the fixed-batch engine workload driven
    twice over IDENTICAL traffic — ``kv_quant`` off (the fp twin) then
    on (int8 KV pages, in-kernel dequant).  The roofline for the quant
    half is recomputed from the quantized bytes (int8 data + f32
    per-slot scales), because lowering that floor is the optimization's
    claim; ``kv_page_bytes`` on/off carries the halved-bytes
    acceptance number and ``outputs_equal`` pins token-identical greedy
    streams.  Works on the CPU tiny models for the accounting smoke;
    absolute times are TPU claims."""
    from paddle_tpu.inference import ContinuousBatchingEngine

    rng = np.random.default_rng(seed)
    prompts = [rng.integers(0, cfg.vocab_size,
                            prompt_len).astype(np.int32)
               for _ in range(slots)]

    def drive(kv_quant):
        eng = ContinuousBatchingEngine(
            model, max_slots=slots, page_size=page_size,
            max_seq_len=max_seq_len, decode_window=decode_window,
            prefill_chunk=prefill_chunk, q_block=q_block,
            kv_quant=kv_quant)
        rids = [eng.add_request(p, new_tokens) for p in prompts]
        t0 = time.perf_counter()
        done = eng.run()
        wall = time.perf_counter() - t0
        return eng, [done[r].sequence for r in rids], wall

    if warm:
        drive(False)
        drive(True)
    eng_fp, out_fp, wall_fp = drive(False)
    eng_q, out_q, wall_q = drive(True)
    toks = eng_q.stats["tokens_generated"]
    toks_fp = eng_fp.stats["tokens_generated"]
    ms_fp = wall_fp * 1e3 / max(toks_fp / slots, 1)
    ms_q = wall_q * 1e3 / max(toks / slots, 1)
    rl_fp = roofline_ms(cfg, model, slots, prompt_len, new_tokens, gbps)
    rl_q = roofline_ms(cfg, model, slots, prompt_len, new_tokens, gbps,
                       kv_itemsize=1, kv_scale_bytes=4)
    row = {
        "batch": slots, "prompt_len": prompt_len,
        "new_tokens": new_tokens, "kv_cache": "paged",
        "decode_window": decode_window, "kv_quant": True,
        "ms_per_token": round(ms_q, 2),
        "tokens_per_sec": round(toks / wall_q, 1),
        "wall_s": round(wall_q, 3),
        "ms_per_token_fp": round(ms_fp, 2),
        # 6-decimal rooflines: the quant row's claim is rl_q < rl_fp,
        # which 3 decimals would erase for the CPU tiny-model smoke
        "roofline_ms": round(rl_q, 6),
        "roofline_ms_fp": round(rl_fp, 6),
        "roofline_x": round(ms_q / rl_q, 1),
        "roofline_x_fp": round(ms_fp / rl_fp, 1),
        "kv_page_bytes": eng_q.stats["kv_page_bytes"],
        "kv_page_bytes_fp": eng_fp.stats["kv_page_bytes"],
        "kv_bytes_ratio": round(eng_q.stats["kv_page_bytes"]
                                / eng_fp.stats["kv_page_bytes"], 3),
        "pages_per_request": round(
            eng_q.stats["pages_allocated"] / slots, 1),
        "outputs_equal": all(
            np.array_equal(a, b) for a, b in zip(out_q, out_fp)),
        "pages_leaked": eng_q.stats["pages_in_use"],   # must be 0
    }
    print(f"quant_b8: {row['ms_per_token']} ms/token vs "
          f"{row['ms_per_token_fp']} fp (roofline x{row['roofline_x']}"
          f" vs x{row['roofline_x_fp']}, kv bytes x"
          f"{row['kv_bytes_ratio']}, outputs_equal="
          f"{row['outputs_equal']})", file=sys.stderr, flush=True)
    return row


def _measure_weight_only(cfg, model, gbps, prompt_len=128,
                         new_tokens=64, seed=5, qmodel=None,
                         warm=True):
    """ISSUE 7 ``weight_only_b1``: single-request paged decode on a
    ``weight_only_quantize``d twin of the bench model — every Linear
    routed through the Pallas fused dequant-matmul — vs the fp model on
    the same prompt.  The roofline weight term is recomputed from int8
    weight + per-channel scale bytes (the weight-byte floor is what
    weight-only quantization buys at batch 1)."""
    import paddle_tpu as paddle
    from paddle_tpu.models.generation import generate
    from paddle_tpu.quantization import weight_only_quantize

    if qmodel is None:
        # deterministic twin: same seed + config rebuilds the weights
        paddle.seed(0)
        qmodel = weight_only_quantize(type(model)(cfg))
        qmodel.eval()
    rng = np.random.default_rng(seed)
    ids = paddle.to_tensor(rng.integers(
        0, cfg.vocab_size, (1, prompt_len)).astype(np.int32))

    def drive(m):
        kw = dict(max_new_tokens=new_tokens, temperature=1.0,
                  kv_cache="paged", decode_window=16)
        out = generate(m, ids, **kw)
        np.asarray(out._read())
        best = float("inf")
        reps = 3 if warm else 1
        for _ in range(reps):
            t0 = time.perf_counter()
            out = generate(m, ids, **kw)
            np.asarray(out._read())
            best = min(best, time.perf_counter() - t0)
        return np.asarray(out._read()), best

    out_fp, wall_fp = drive(model)
    out_q, wall_q = drive(qmodel)
    pb_fp = _param_bytes(model)
    pb_q = _quant_param_bytes(model)
    rl_fp = roofline_ms(cfg, model, 1, prompt_len, new_tokens, gbps)
    rl_q = roofline_ms(cfg, model, 1, prompt_len, new_tokens, gbps,
                       param_bytes=pb_q)
    row = {
        "batch": 1, "prompt_len": prompt_len, "new_tokens": new_tokens,
        "kv_cache": "paged", "decode_window": 16, "weight_only": "int8",
        "ms_per_token": round(wall_q * 1e3 / new_tokens, 2),
        "ms_per_token_fp": round(wall_fp * 1e3 / new_tokens, 2),
        "tokens_per_sec": round(new_tokens / wall_q, 1),
        "wall_s": round(wall_q, 3),
        "roofline_ms": round(rl_q, 6),
        "roofline_ms_fp": round(rl_fp, 6),
        "roofline_x": round(wall_q * 1e3 / new_tokens / rl_q, 1),
        "roofline_x_fp": round(wall_fp * 1e3 / new_tokens / rl_fp, 1),
        "weight_bytes": pb_q,
        "weight_bytes_fp": pb_fp,
        "weight_bytes_ratio": round(pb_q / pb_fp, 3),
        "outputs_equal": bool(np.array_equal(out_q, out_fp)),
    }
    print(f"weight_only_b1: {row['ms_per_token']} ms/token vs "
          f"{row['ms_per_token_fp']} fp (weight bytes x"
          f"{row['weight_bytes_ratio']}, roofline x{row['roofline_x']}"
          f" vs x{row['roofline_x_fp']})", file=sys.stderr, flush=True)
    return row


def _measure_speculative(cfg, model, slots=4, max_seq_len=512,
                         prompt_len=64, motif_len=8, new_tokens=48,
                         n_requests=8, spec_k=4, page_size=16,
                         decode_window=16, prefill_chunk=128,
                         q_block=8, seed=7, warm=True):
    """ISSUE 9 ``speculative`` row: repetitive-text traffic (each
    prompt tiles its own short motif) through the engine twice over
    IDENTICAL arrivals — ``spec_decode`` off, then on with the
    model-free n-gram proposer.  The verify multiplier is
    ``accepted_tokens_per_step`` (mean tokens emitted per slot per
    verify dispatch); ``outputs_equal`` pins the bitwise-greedy claim.
    Works on the CPU tiny models (the accounting smoke in
    tests/test_speculative.py drives it); absolute times are
    TPU-measured."""
    from paddle_tpu.inference import ContinuousBatchingEngine

    rng = np.random.default_rng(seed)
    prompts = []
    for _ in range(n_requests):
        motif = rng.integers(0, cfg.vocab_size,
                             motif_len).astype(np.int32)
        prompts.append(np.tile(motif, -(-prompt_len // motif_len))
                       [:prompt_len])

    def drive(spec):
        eng = ContinuousBatchingEngine(
            model, max_slots=slots, page_size=page_size,
            max_seq_len=max_seq_len, decode_window=decode_window,
            prefill_chunk=prefill_chunk, q_block=q_block,
            spec_decode=spec, spec_k=spec_k)
        rids = [eng.add_request(p, new_tokens) for p in prompts]
        t0 = time.perf_counter()
        done = eng.run()
        wall = time.perf_counter() - t0
        return eng, [done[r].sequence for r in rids], wall

    if warm:                       # compile + warm both program sets
        drive(False)
        drive(True)
    eng_off, out_off, wall_off = drive(False)
    eng_on, out_on, wall_on = drive(True)
    st = eng_on.stats
    row = {
        "batch": slots, "prompt_len": prompt_len,
        "new_tokens": new_tokens, "kv_cache": "paged",
        "spec_k": spec_k, "proposer": "ngram",
        "requests": n_requests,
        "tokens_per_sec": round(
            st["tokens_generated"] / wall_on, 1),
        "tokens_per_sec_plain": round(
            eng_off.stats["tokens_generated"] / wall_off, 1),
        "wall_s": round(wall_on, 3),
        # mean tokens emitted per slot per verify dispatch — the
        # decode-throughput multiplier speculation buys
        "accepted_tokens_per_step": round(
            _tl_mean(eng_on, "spec_accepted_per_step"), 2),
        "spec_accept_rate": st["spec_accept_rate"],
        "spec_proposed": st["spec_proposed"],
        "spec_accepted": st["spec_accepted"],
        "dispatches": st["decode_dispatches"],
        "dispatches_plain": eng_off.stats["decode_dispatches"],
        "outputs_equal": all(
            np.array_equal(a, b) for a, b in zip(out_on, out_off)),
        "pages_leaked": st["pages_in_use"],   # must be 0
    }
    print(f"speculative: {row['accepted_tokens_per_step']} accepted "
          f"tokens/step (accept rate {row['spec_accept_rate']}), "
          f"{row['tokens_per_sec']} tok/s vs "
          f"{row['tokens_per_sec_plain']} plain, outputs_equal="
          f"{row['outputs_equal']}", file=sys.stderr, flush=True)
    return row


def _measure_tp(cfg, model, gbps, tp, slots=8, prompt_len=128,
                new_tokens=64, page_size=16, decode_window=16,
                prefill_chunk=128, q_block=8, max_seq_len=512, seed=8,
                warm=True):
    """ISSUE 13 ``tp2``/``tp4`` rows: the fixed-batch engine workload
    driven twice over IDENTICAL traffic — single-device, then
    TP-sharded over a ``tp``-device mesh axis (weights column/row
    split, KV pools sharded by kv-head, one psum at the attention
    output and MLP reduce).  The roofline for the TP half is the
    PER-DEVICE floor: each shard reads ``1/tp`` of the weight and KV
    bytes, so the target column is ``roofline_ms / tp`` — the whole
    point of the cut is to move the floor itself.  ``outputs_equal``
    pins token-identical greedy streams.  Works on the CPU mesh for
    the accounting smoke; absolute times are TPU claims."""
    import jax
    from jax.sharding import Mesh

    from paddle_tpu.inference import ContinuousBatchingEngine

    if len(jax.devices()) < tp:
        return {"skipped": f"needs {tp} devices, have "
                           f"{len(jax.devices())}"}
    mesh = Mesh(np.asarray(jax.devices()[:tp]), ("tp",))
    rng = np.random.default_rng(seed)
    prompts = [rng.integers(0, cfg.vocab_size,
                            prompt_len).astype(np.int32)
               for _ in range(slots)]

    def drive(m):
        eng = ContinuousBatchingEngine(
            model, max_slots=slots, page_size=page_size,
            max_seq_len=max_seq_len, decode_window=decode_window,
            prefill_chunk=prefill_chunk, q_block=q_block, mesh=m)
        rids = [eng.add_request(p, new_tokens) for p in prompts]
        t0 = time.perf_counter()
        done = eng.run()
        wall = time.perf_counter() - t0
        return eng, [done[r].sequence for r in rids], wall

    if warm:
        drive(None)
        drive(mesh)
    eng_1, out_1, wall_1 = drive(None)
    eng_tp, out_tp, wall_tp = drive(mesh)
    toks = eng_tp.stats["tokens_generated"]
    ms_1 = wall_1 * 1e3 / max(eng_1.stats["tokens_generated"] / slots,
                              1)
    ms_tp = wall_tp * 1e3 / max(toks / slots, 1)
    rl_1 = roofline_ms(cfg, model, slots, prompt_len, new_tokens, gbps)
    rl_tp = rl_1 / tp                  # per-device bytes: weights + KV
    row = {                            # shards both split tp ways
        "batch": slots, "prompt_len": prompt_len,
        "new_tokens": new_tokens, "kv_cache": "paged",
        "decode_window": decode_window, "tp": tp,
        "ms_per_token": round(ms_tp, 2),
        "tokens_per_sec": round(toks / wall_tp, 1),
        "wall_s": round(wall_tp, 3),
        "ms_per_token_1dev": round(ms_1, 2),
        "roofline_ms": round(rl_tp, 6),
        "roofline_ms_1dev": round(rl_1, 6),
        "roofline_x": round(ms_tp / rl_tp, 1),
        "roofline_x_1dev": round(ms_1 / rl_1, 1),
        "outputs_equal": all(
            np.array_equal(a, b) for a, b in zip(out_tp, out_1)),
        "pages_leaked": eng_tp.stats["pages_in_use"],   # must be 0
    }
    print(f"tp{tp}: {row['ms_per_token']} ms/token vs "
          f"{row['ms_per_token_1dev']} on 1 dev (per-device roofline "
          f"x{row['roofline_x']}, outputs_equal="
          f"{row['outputs_equal']})", file=sys.stderr, flush=True)
    return row


def _measure_disagg(cfg, model, slots=6, prompt_len=64, new_tokens=48,
                    storm_prompt=256, storm_new=4, n_latency=6,
                    n_storm=12, page_size=16, decode_window=16,
                    prefill_chunk=128, max_seq_len=512, q_block=8,
                    seed=9, warm=True):
    """ISSUE 13 ``disagg`` row: a latency class (medium prompt, long
    decode) served alone and then under a concurrent PREFILL STORM
    (long prompts, trivial decode) — first on one colocated engine,
    then through ``inference.DisaggServer`` (prefill and decode worker
    groups with the KV-page handoff).  The claim is the decode-p99
    shape: colocated p99 tracks the storm (prefill chunks steal mixed
    dispatches from residents' decodes), the disagg decode group's
    stays flat because prefill compute is physically elsewhere.
    Reports ``tpot_p99_ms_*`` for all four cells plus the handoff
    accounting (``handoff_ms_avg``, ``transfer_bytes``,
    ``handoffs``)."""
    from paddle_tpu.inference import (ContinuousBatchingEngine,
                                      DisaggServer)

    rng = np.random.default_rng(seed)
    lat = [rng.integers(0, cfg.vocab_size,
                        prompt_len).astype(np.int32)
           for _ in range(n_latency)]
    storm = [rng.integers(0, cfg.vocab_size,
                          storm_prompt).astype(np.int32)
             for _ in range(n_storm)]
    kw = dict(max_slots=slots, page_size=page_size,
              max_seq_len=max_seq_len, decode_window=decode_window,
              prefill_chunk=prefill_chunk, q_block=q_block)

    def drive_colocated(with_storm):
        eng = ContinuousBatchingEngine(model, **kw)
        for p in lat:
            eng.add_request(p, new_tokens)
        pending = list(storm) if with_storm else []
        while eng.has_work or pending:
            if pending:                        # storm arrivals: 2/step
                for _ in range(2):
                    if pending:
                        eng.add_request(pending.pop(0), storm_new)
            eng.step()
        return eng

    def drive_disagg(with_storm):
        # the decode group carries the SLO spec: disaggregation exists
        # to protect decode TPOT tails, so that is where the judgment
        # layer watches (slo_ok/budget_burn columns below)
        srv = DisaggServer(model, prefill_kwargs=dict(kw),
                           decode_kwargs=dict(kw, slo=_SLO_SPEC))
        for p in lat:
            srv.add_request(p, new_tokens)
        pending = list(storm) if with_storm else []
        while srv.has_work or pending:
            if pending:
                for _ in range(2):
                    if pending:
                        srv.add_request(pending.pop(0), storm_new)
            srv.step()
        return srv

    if warm:
        drive_colocated(True)
        drive_disagg(True)
    co_calm = drive_colocated(False)
    co_storm = drive_colocated(True)
    dg_calm = drive_disagg(False)
    dg_storm = drive_disagg(True)
    st = dg_storm.stats
    dec = dg_storm.decode_group[0]
    row = {
        "batch": slots, "prompt_len": prompt_len,
        "new_tokens": new_tokens, "kv_cache": "paged",
        "storm_prompt": storm_prompt, "storm_requests": n_storm,
        "requests": n_latency,
        # the p99 grid: colocated decode latency degrades under the
        # storm; the disagg decode group's should not
        "tpot_p99_ms_colocated": round(
            _tl_pct(co_calm, "tpot_ms"), 3),
        "tpot_p99_ms_colocated_storm": round(
            _tl_pct(co_storm, "tpot_ms"), 3),
        "tpot_p99_ms_disagg": round(
            _tl_pct(dg_calm.decode_group[0], "tpot_ms"), 3),
        "tpot_p99_ms_disagg_storm": round(
            _tl_pct(dec, "tpot_ms"), 3),
        "tpot_ms_avg_colocated_storm": round(
            _tl_mean(co_storm, "tpot_ms"), 3),
        "tpot_ms_avg_disagg_storm": round(
            _tl_mean(dec, "tpot_ms"), 3),
        "handoffs": st["handoffs"],
        "transfer_bytes": st["handoff_bytes"],
        "handoff_ms_avg": round(
            _disagg_handoff_mean(dg_storm), 3),
        "requeues": st["requeues"],
        "pages_leaked": (st["prefill_pages_in_use"]
                         + st["decode_pages_in_use"]),   # must be 0
        # decode-group SLO verdict under the storm (ISSUE 14)
        **_slo_cols(dec),
    }
    print(f"disagg: decode p99 {row['tpot_p99_ms_disagg']} -> "
          f"{row['tpot_p99_ms_disagg_storm']} ms under storm (vs "
          f"colocated {row['tpot_p99_ms_colocated']} -> "
          f"{row['tpot_p99_ms_colocated_storm']}), "
          f"{row['handoffs']} handoffs, "
          f"{row['transfer_bytes']} bytes, "
          f"{row['handoff_ms_avg']} ms/handoff", file=sys.stderr,
          flush=True)
    return row


def _merged_tl_pct(engines, name, q=0.95) -> float:
    """Percentile of one timeline histogram MERGED across replicas:
    the fixed log-spaced buckets are identical on every registry, so
    fleet-wide tails are a bucket-count sum away (the same shared
    ``percentile_from_counts`` math as the single-engine columns)."""
    from paddle_tpu.observability.metrics import percentile_from_counts
    buckets, counts, total = [], [], 0
    for eng in engines:
        node = _tl_node(eng, name)
        if not node.get("count"):
            continue
        if not buckets:
            buckets = list(node["buckets"])
            counts = [0] * len(node["counts"])
        counts = [a + b for a, b in zip(counts, node["counts"])]
        total += node["count"]
    return percentile_from_counts(buckets, counts, total, q)


def _measure_fleet(cfg, model, slots=4, prompt_len=64, new_tokens=24,
                   shared_groups=4, group_size=4, n_light=4,
                   light_new=8, page_size=16, decode_window=16,
                   prefill_chunk=64, max_seq_len=256, q_block=8,
                   kill_step=3, seed=11, warm=True):
    """ISSUE 17 ``fleet`` row: the multi-replica router's three claims
    measured on one skewed-tenant workload (a ``storm`` tenant flooding
    shared-prefix groups plus a light ``interactive`` tenant).

    * CAPACITY — the same traffic through 4 routed replicas vs 1:
      fleet TTFT p95 (merged replica histograms) and goodput drop
      with fleet width.
    * AFFINITY — prefix-cache-aware placement vs round-robin on the
      same shared-prefix storm: fleet-wide cache-hit token fraction
      (affinity concentrates each group where its pages live; RR
      scatters them, so every replica re-prefills the prefix).
    * RECOVERY — a 3-replica fleet with one replica killed mid-decode:
      ``recover_ms`` (kill -> every affected request completed on a
      survivor), ``requeued``, ``outputs_equal`` vs the unfaulted run
      (greedy decode is batch-invariant, so this must be True) and
      ``pages_leaked`` on the survivors (must be 0)."""
    from paddle_tpu.inference import FleetRouter, TenantSpec
    from paddle_tpu.resilience import faults

    rng = np.random.default_rng(seed)
    prefix_len = prompt_len // 2
    groups = []
    for _ in range(shared_groups):
        prefix = rng.integers(0, cfg.vocab_size,
                              prefix_len).astype(np.int32)
        groups.append([np.concatenate([
            prefix, rng.integers(0, cfg.vocab_size,
                                 prompt_len - prefix_len)
            .astype(np.int32)]) for _ in range(group_size)])
    # leaders warm each group's prefix onto SOME replica; the storm is
    # the remaining members interleaved across groups (consecutive
    # arrivals from different groups — the placement decision affinity
    # must get right and round-robin gets right only by luck)
    leaders = [g[0] for g in groups]
    storm = [g[i] for i in range(1, group_size) for g in groups]
    light = [rng.integers(0, cfg.vocab_size,
                          prompt_len // 4).astype(np.int32)
             for _ in range(n_light)]
    kw = dict(max_slots=slots, page_size=page_size,
              max_seq_len=max_seq_len, decode_window=decode_window,
              prefill_chunk=prefill_chunk, q_block=q_block)
    tenants = [TenantSpec("storm", weight=1.0),
               TenantSpec("interactive", weight=4.0, priority=0)]

    def drive(n_replicas, affinity, kill=None):
        faults.clear()
        r = FleetRouter(model, replicas=n_replicas, replica_kwargs=kw,
                        tenants=tenants, affinity=affinity)
        done = {}
        # warm phase: the trie publishes pages at retirement, so each
        # group's leader runs to completion first — its prefix lands
        # on SOME replica's cache, which is the steady-state a fleet
        # front-end lives in (system prompts already resident)
        for p in leaders:
            r.add_request(p, new_tokens, tenant="storm")
        done.update(r.run())
        # storm phase: the rest arrive staggered 2/step
        pending = [(p, new_tokens, "storm") for p in storm]
        for i, p in enumerate(light):
            pending.insert(3 * i + 1, (p, light_new, "interactive"))
        affected, t_kill, t_rec, step = None, None, None, 0
        while r.has_work or pending:
            for _ in range(2):
                if pending:
                    p, n, t = pending.pop(0)
                    r.add_request(p, n, tenant=t)
            if kill is not None and step == kill:
                affected = set(r._by_name("r1").rids)
                faults.inject("router_replica_lost", "r1")
                t_kill = time.perf_counter()
            for c in r.step():
                done[c.request_id] = c
            if (affected is not None and t_rec is None
                    and affected <= set(done)):
                t_rec = time.perf_counter()
            step += 1
            assert step < 100000, "fleet bench wedged"
        rec_ms = ((t_rec - t_kill) * 1e3
                  if t_kill is not None and t_rec is not None else 0.0)
        return r, done, rec_ms, (len(affected) if affected else 0)

    if warm:
        drive(1, True)
    t0 = time.perf_counter()
    r4, d4, _, _ = drive(4, True)
    wall4 = time.perf_counter() - t0
    t0 = time.perf_counter()
    r1, d1, _, _ = drive(1, True)
    wall1 = time.perf_counter() - t0
    rrr, drr, _, _ = drive(4, False)

    def live_engines(r):
        return [rep.engine for rep in r._replicas
                if rep.state != "dead"]

    def hit_frac(r):
        hit = req = 0
        for e in live_engines(r):
            s = e.stats
            hit += s["cache_hit_tokens"]
            req += s["prefill_tokens_requested"]
        return hit / req if req else 0.0

    def goodput(r, done):
        ok = sum(1 for c in done.values()
                 if c.finish_reason in ("stop", "length"))
        return ok / len(done) if done else 0.0

    # recovery drill: 3 replicas, kill r1 mid-decode, compare to the
    # unfaulted 3-replica run request-by-request
    r3c, d3c, _, _ = drive(3, True)
    r3f, d3f, rec_ms, requeued = drive(3, True, kill=kill_step)
    outputs_equal = (sorted(d3c) == sorted(d3f) and all(
        np.array_equal(d3c[k].tokens, d3f[k].tokens) for k in d3c))
    leaked = sum(e.stats["pages_in_use"] for e in live_engines(r3f))

    row = {
        "replicas": 4, "batch": slots, "prompt_len": prompt_len,
        "new_tokens": new_tokens,
        "requests": len(storm) + len(light), "kv_cache": "paged",
        "ttft_p95_ms_fleet4": round(
            _merged_tl_pct(live_engines(r4), "ttft_ms", 0.95), 3),
        "ttft_p95_ms_fleet1": round(
            _merged_tl_pct(live_engines(r1), "ttft_ms", 0.95), 3),
        "goodput_fleet4": round(goodput(r4, d4), 4),
        "goodput_fleet1": round(goodput(r1, d1), 4),
        "tokens_per_sec_fleet4": round(
            sum(e.stats["tokens_generated"]
                for e in live_engines(r4)) / wall4, 1),
        "tokens_per_sec_fleet1": round(
            sum(e.stats["tokens_generated"]
                for e in live_engines(r1)) / wall1, 1),
        "cache_hit_frac_affinity": round(hit_frac(r4), 4),
        "cache_hit_frac_rr": round(hit_frac(rrr), 4),
        "recover_ms": round(rec_ms, 3),
        "requeued": requeued,
        "deaths": r3f.stats["deaths"],
        "outputs_equal": bool(outputs_equal),
        "pages_leaked": int(leaked),   # must be 0
    }
    print(f"fleet: ttft p95 {row['ttft_p95_ms_fleet1']} -> "
          f"{row['ttft_p95_ms_fleet4']} ms at 4 replicas, cache-hit "
          f"{row['cache_hit_frac_rr']:.0%} (rr) -> "
          f"{row['cache_hit_frac_affinity']:.0%} (affinity), "
          f"replica kill: {row['requeued']} requeued, recovered in "
          f"{row['recover_ms']} ms, outputs_equal="
          f"{row['outputs_equal']}", file=sys.stderr, flush=True)
    return row


def _measure_migration(cfg, model, slots=4, prompt_len=64,
                       new_tokens=24, n_requests=6, page_size=16,
                       decode_window=16, prefill_chunk=64,
                       max_seq_len=256, q_block=8, drain_step=3,
                       seed=13, warm=True):
    """ISSUE 20 ``migration`` columns (merged onto the ``fleet`` row):
    graceful drain measured BOTH ways on one 2-replica workload —
    ``drain_ms_migrate`` (live migration on: residents ship warm over
    ``KVPageTransport`` and the drained replica parks as soon as the
    transfers land) vs ``drain_ms_wait`` (cold drain: the replica
    waits out every resident decode before parking).
    ``migrated_pages`` counts the KV pages that actually moved;
    ``prefill_tokens_saved`` prices them (pages * page_size — every
    shipped page is a page of already-computed tokens the destination
    did NOT recompute, exactly what the PR17 cold requeue would have
    re-prefilled); ``outputs_equal`` gates the row: both drained runs
    must be bitwise the undrained run (greedy decode is deterministic
    and batch-invariant, so migration is scheduling, never semantics).
    Absolute times are TPU claims; the CPU smoke gates semantics."""
    from paddle_tpu.inference import FleetRouter

    rng = np.random.default_rng(seed)
    prompts = [rng.integers(0, cfg.vocab_size,
                            prompt_len).astype(np.int32)
               for _ in range(n_requests)]
    kw = dict(max_slots=slots, page_size=page_size,
              max_seq_len=max_seq_len, decode_window=decode_window,
              prefill_chunk=prefill_chunk, q_block=q_block)

    def drive(drain, migration):
        r = FleetRouter(model, replicas=2, replica_kwargs=kw,
                        migration=migration)
        rids = [r.add_request(p, new_tokens) for p in prompts]
        done, step, t_drain, t_parked = {}, 0, None, None
        while r.has_work:
            if drain and step == drain_step:
                t_drain = time.perf_counter()
                r.drain("r0")
            for c in r.step():
                done[c.request_id] = c
            if (t_drain is not None and t_parked is None
                    and r.replica_states()["r0"] == "standby"):
                t_parked = time.perf_counter()
            step += 1
            assert step < 100000, "migration bench wedged"
        if t_drain is not None and t_parked is None:
            t_parked = time.perf_counter()
        drain_ms = ((t_parked - t_drain) * 1e3
                    if t_drain is not None else 0.0)
        return r, rids, done, drain_ms

    if warm:
        drive(False, False)
    _, rids0, base, _ = drive(False, False)       # no drain: the bar
    rm, rids_m, dm, ms_migrate = drive(True, True)
    rw, rids_w, dw, ms_wait = drive(True, False)
    outputs_equal = all(
        np.array_equal(base[a].tokens, dm[b].tokens)
        and np.array_equal(base[a].tokens, dw[c].tokens)
        for a, b, c in zip(rids0, rids_m, rids_w))
    leaked = sum(rep.engine.stats["pages_in_use"]
                 for rep in rm._replicas if rep.state != "dead")
    row = {
        "drain_ms_migrate": round(ms_migrate, 3),
        "drain_ms_wait": round(ms_wait, 3),
        "migrated_pages": int(rm.stats["migrated_pages"]),
        "prefill_tokens_saved": int(rm.stats["migrated_pages"]
                                    * page_size),
        "migration_failures": int(rm.stats["migration_failures"]),
        "outputs_equal": bool(outputs_equal),
        "pages_leaked": int(leaked),   # must be 0
    }
    print(f"migration: drain {row['drain_ms_wait']} ms (cold wait) -> "
          f"{row['drain_ms_migrate']} ms (live migrate), "
          f"{row['migrated_pages']} pages shipped warm "
          f"({row['prefill_tokens_saved']} prefill tokens saved), "
          f"outputs_equal={row['outputs_equal']}",
          file=sys.stderr, flush=True)
    return row


def _disagg_handoff_mean(srv) -> float:
    node = srv.metrics()
    for part in ("serving", "handoff_ms"):
        node = node.get(part, {})
    cnt = node.get("count", 0)
    return node.get("sum", 0.0) / cnt if cnt else 0.0


def _measure_metrics_overhead(cfg, model, slots=6, prompt_len=32,
                              new_tokens=24, page_size=16,
                              decode_window=8, prefill_chunk=64,
                              max_seq_len=128, q_block=8, reps=3,
                              n_requests=None, warm=True):
    """ISSUE 8 ``metrics_overhead``: IDENTICAL traffic through the
    engine with ``PDTPU_METRICS`` on vs off, reporting the tokens/sec
    delta.  The observability runtime's always-on claim is that the on
    state costs <= 3% tokens/sec on the serving hot loop — this row is
    the number behind that claim (best-of-``reps`` walls each way so
    scheduler noise doesn't masquerade as metric cost).  Since
    ISSUE 14 the engine runs with the SLO guardrails and the stall
    watchdog ARMED, so the gate covers judgment-layer cost too (both
    are metrics-flag-gated no-ops in the off half).  Runs on the CPU
    tiny models for the smoke test; the TPU measurement is the claim
    of record."""
    import paddle_tpu as paddle
    from paddle_tpu.inference import ContinuousBatchingEngine

    rng = np.random.default_rng(6)
    prompts = [rng.integers(0, cfg.vocab_size,
                            prompt_len).astype(np.int32)
               for _ in range(n_requests or 2 * slots)]

    def drive():
        # guardrails ARMED (ISSUE 14): the overhead claim covers SLO
        # evaluation + the per-dispatch watchdog arm/disarm, not just
        # bare metrics — they ride the existing event stream, so the
        # row must prove they add no per-token host sync.  With
        # metrics off both are no-ops, so the off half stays the
        # pre-observability baseline.
        eng = ContinuousBatchingEngine(
            model, max_slots=slots, page_size=page_size,
            max_seq_len=max_seq_len, decode_window=decode_window,
            prefill_chunk=prefill_chunk, q_block=q_block,
            slo=_SLO_SPEC, watchdog_ms=_WATCHDOG_MS)
        for p in prompts:
            eng.add_request(p, new_tokens)
        t0 = time.perf_counter()
        eng.run()
        wall = time.perf_counter() - t0
        return eng.stats["tokens_generated"], wall

    def timed(flag):
        paddle.set_flags({"metrics": flag})
        return drive()

    old = paddle.get_flags("metrics")["metrics"]
    try:
        if warm:                # compile + warm both flag states
            timed(False)
            timed(True)
        # INTERLEAVED best-of: alternate off/on within each rep so a
        # monotonic machine-load drift (cache warming, a background
        # compile, CPU frequency) biases both states equally instead
        # of charging the later state with it
        toks_off = toks_on = 0
        wall_off = wall_on = float("inf")
        for _ in range(reps):
            t, w = timed(False)
            if w < wall_off:
                toks_off, wall_off = t, w
            t, w = timed(True)
            if w < wall_on:
                toks_on, wall_on = t, w
    finally:
        paddle.set_flags({"metrics": old})
    tps_off = toks_off / wall_off
    tps_on = toks_on / wall_on
    row = {
        "batch": slots, "prompt_len": prompt_len,
        "new_tokens": new_tokens, "kv_cache": "paged",
        "decode_window": decode_window, "requests": len(prompts),
        "tokens_per_sec": round(tps_on, 1),
        "tokens_per_sec_off": round(tps_off, 1),
        "wall_s": round(wall_on, 3),
        "wall_s_off": round(wall_off, 3),
        # the acceptance number: fractional tokens/sec given up by
        # leaving metrics on (negative = noise floor; gate is <= 0.03)
        "overhead_frac": round(max(0.0, 1.0 - tps_on / tps_off), 4),
    }
    print(f"metrics_overhead: {row['tokens_per_sec']} tok/s on vs "
          f"{row['tokens_per_sec_off']} off "
          f"({row['overhead_frac']:.1%} overhead)", file=sys.stderr,
          flush=True)
    return row


# the serving rows' validity depends on the engine's scheduling layer
# and its policy knobs (core/state.py serving_* flags, resilience
# guard/retry), not just the kernels — include them in code_version so
# policy changes re-measure
FILES = ["benchmarks/serving_bench.py",
         "paddle_tpu/models/generation.py",
         "paddle_tpu/inference/engine.py",
         "paddle_tpu/inference/prefix_cache.py",
         "paddle_tpu/inference/speculative.py",
         # disaggregated/TP serving (ISSUE 13): the tp2/tp4/disagg
         # rows and every engine row's scheduling layer ride these
         "paddle_tpu/inference/distserve.py",
         # fleet router (ISSUE 17): the fleet row's placement, QoS and
         # replica-kill recovery all ride it
         "paddle_tpu/inference/router.py",
         "paddle_tpu/resilience/serving.py",
         # live migration (ISSUE 20): the fleet row's drain/migration
         # columns ride snapshot/restore + the preempt flag
         "paddle_tpu/resilience/preempt.py",
         "paddle_tpu/core/state.py",
         "paddle_tpu/ops/pallas/paged_attention.py",
         "paddle_tpu/ops/pallas/flash_attention.py",
         "paddle_tpu/ops/pallas/quant_matmul.py",
         # decode megakernel (ISSUE 18): the fused per-layer decode
         # chain every paged/continuous row will run once the
         # serving_megakernel flag defaults on — kernel edits must
         # re-measure the serving rows
         "paddle_tpu/ops/pallas/fused_decode_qkv.py",
         "paddle_tpu/ops/pallas/fused_decode_mlp.py",
         "paddle_tpu/quantization/__init__.py",
         # the observability runtime rides the serving hot loop (event
         # emission + timeline observes per dispatch/token): edits to
         # it re-measure every serving row on the next TPU run
         "paddle_tpu/observability/metrics.py",
         "paddle_tpu/observability/events.py",
         "paddle_tpu/observability/serving.py",
         # dispatch tracing spans (ISSUE 12) ride every engine
         # dispatch: span cost is part of the metrics_overhead claim
         "paddle_tpu/observability/tracing.py",
         # SLO guardrails + stall watchdog (ISSUE 14) arm the
         # metrics_overhead row and feed the slo_ok/budget_burn
         # columns: their code must re-measure the serving rows
         "paddle_tpu/observability/slo.py",
         "paddle_tpu/observability/watchdog.py"]


def cached_rows(dev):
    """Previously measured serving rows for this device kind, or None
    (bench.py embeds these without re-measuring)."""
    import measured_cache as mc
    kind = str(getattr(dev, "device_kind", dev.platform))
    return mc.load(kind, "serving", mc.code_version(*FILES))


def main():
    import jax

    import measured_cache as mc

    dev = jax.devices()[0]
    if dev.platform != "tpu":
        print("serving_bench: not on TPU; skipping", file=sys.stderr)
        return 0
    kind = str(getattr(dev, "device_kind", dev.platform))
    ver = mc.code_version(*FILES)
    rows = mc.load(kind, "serving", ver)
    if rows is None:
        rows = measure()
        mc.store(kind, "serving", ver, rows)
    print(json.dumps({"serving": rows}, indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Serving measurements (VERDICT r4 items 3/8): ms/token for windowed
decode with dense and paged KV caches, plus a multi-request
batched-decode row over the page pools (the continuous-batching
precursor). Reference bar: the fused serving kernels
``paddle/phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu``
and ``masked_multihead_attention_kernel.cu`` (SURVEY C12/C13).

Results persist via benchmarks/measured_cache.py and surface as a
compact ``serving`` entry in bench.py's enriched record and in
BASELINE.md. Run standalone on the real chip:

    PYTHONPATH=/root/repo:/root/.axon_site python benchmarks/serving_bench.py
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
os.environ.setdefault(
    "PDTPU_CACHE_DIR", os.path.join(_REPO, "benchmarks", "measured"))


def _build_model():
    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    cfg = GPTConfig(vocab_size=50304, hidden_size=768, num_layers=12,
                    num_heads=12, max_seq_len=2048, dropout=0.0)
    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    model.eval()
    return cfg, model


def measure():
    import paddle_tpu as paddle
    from paddle_tpu.models.generation import generate

    cfg, model = _build_model()
    rng = np.random.default_rng(0)
    rows = {}

    def run(name, batch, prompt_len, new_tokens, kv, window):
        ids = paddle.to_tensor(
            rng.integers(0, cfg.vocab_size,
                         (batch, prompt_len)).astype(np.int32))
        kw = dict(max_new_tokens=new_tokens, temperature=0.0,
                  kv_cache=kv, decode_window=window)
        out = generate(model, ids, **kw)       # compile + warm
        np.asarray(out._read())
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            out = generate(model, ids, **kw)
            np.asarray(out._read())            # full sync readback
            best = min(best, time.perf_counter() - t0)
        ms_tok = best * 1e3 / new_tokens
        rows[name] = {
            "batch": batch, "prompt_len": prompt_len,
            "new_tokens": new_tokens, "kv_cache": kv,
            "decode_window": window,
            "ms_per_token": round(ms_tok, 2),
            "tokens_per_sec": round(batch * new_tokens / best, 1),
            "wall_s": round(best, 3),
        }
        print(f"{name}: {ms_tok:.2f} ms/token "
              f"({rows[name]['tokens_per_sec']} tok/s)",
              file=sys.stderr, flush=True)

    # single-request latency rows (the r4 commit's claimed measurement,
    # now recorded): 128-token prompt, 64 new tokens, windowed decode
    run("dense_b1", 1, 128, 64, "dense", 16)
    run("paged_b1", 1, 128, 64, "paged", 16)
    # multi-request batched decode over the page pools: 8 concurrent
    # sequences through one compiled windowed-decode program — the
    # static precursor of continuous batching (per-sequence block
    # tables already admit ragged lengths)
    run("paged_b8", 8, 128, 64, "paged", 16)
    # long-context serving check: 1024-token prompt, paged
    run("paged_b1_long", 1, 1024, 64, "paged", 16)
    return rows


FILES = ["benchmarks/serving_bench.py",
         "paddle_tpu/models/generation.py",
         "paddle_tpu/ops/pallas/paged_attention.py",
         "paddle_tpu/ops/pallas/flash_attention.py"]


def cached_rows(dev):
    """Previously measured serving rows for this device kind, or None
    (bench.py embeds these without re-measuring)."""
    import measured_cache as mc
    kind = str(getattr(dev, "device_kind", dev.platform))
    return mc.load(kind, "serving", mc.code_version(*FILES))


def main():
    import jax

    import measured_cache as mc

    dev = jax.devices()[0]
    if dev.platform != "tpu":
        print("serving_bench: not on TPU; skipping", file=sys.stderr)
        return 0
    kind = str(getattr(dev, "device_kind", dev.platform))
    ver = mc.code_version(*FILES)
    rows = mc.load(kind, "serving", ver)
    if rows is None:
        rows = measure()
        mc.store(kind, "serving", ver, rows)
    print(json.dumps({"serving": rows}, indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
